"""Device-mesh sharding: scale the lockstep tick over groups.

This is the TPU-native replacement for the reference's "distributed communication
backend" (plaintext unary gRPC-over-Netty, one channel per peer —
reference RaftClient.kt:14-16, greeter.proto:46-49). The design inverts the topology:
a Raft *group* never spans devices — every intra-group "RPC" is an in-register array
op inside one jitted tick — and the *groups axis* is sharded over the device mesh, so
the only cross-device traffic is metrics aggregation (psum-style reductions XLA lowers
onto ICI/DCN). Within a tick there are ZERO collectives.

Two execution paths (make_sharded_run's `impl`):
- "xla": plain `jit` + `NamedSharding` — every per-tick op is elementwise over groups
  and all randomness is counted threefry (`jax_threefry_partitionable`), so XLA's
  SPMD partitioner splits the whole tick shard-locally with no communication.
- "pallas": the ops/pallas_tick.py megakernel per shard via `jax.shard_map`; the
  RNG/aux pre/post passes stay globally-sharded XLA (same partitioning argument), so
  the kernel needs no global group offsets.

The mesh is 2-D, ("dcn", "ici"): the outer axis models the multi-host/DCN dimension
and the inner axis the within-host ICI dimension, matching how a v4 pod slice is
addressed. Groups shard over both (flattened), so one group count scales from 1 chip
to a full pod without touching the kernel.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from raft_kotlin_tpu.models.state import RaftState, init_state
from raft_kotlin_tpu.ops.tick import make_tick
from raft_kotlin_tpu.utils import telemetry as telemetry_mod
from raft_kotlin_tpu.utils.config import RaftConfig
from raft_kotlin_tpu.constants import LEADER


def shard_map_compat(f, mesh, in_specs, out_specs, check_vma=False):
    """`jax.shard_map` across jax versions: the top-level binding (with its
    `check_vma` kwarg) only exists on newer jax; older installs carry the
    same transform as `jax.experimental.shard_map.shard_map` with the
    equivalent check spelled `check_rep`. Every shard_map call site in this
    package routes through here so one jax pin change cannot silently
    disable the sharded engines."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma)


# ---------------------------------------------------------------------------
# Shape-aware deep-engine routing (round 6; VERDICT r5 weak #5 / missing #1).
#
# The deep band has three bit-identical per-shard engines with different cost
# structures: "fc" (frontier-value cache — pays per-tick (G,) cache algebra to
# avoid log takes), "batched" (plain batched engine — pays the take/scatter op
# floors every tick), and "flat" (per-pair flat engine — the round-2 sharded
# program; no batching, ~7 log ops per pair). Which one wins is a function of
# the SHAPE (log capacity C x per-shard lane width G), not of the platform.
#
# Since round 13 the crossover data lives in the UNIFIED tuning table
# (parallel/autotune.py — one plan layer for engine + ILP + fused-tick +
# sharding routing, measure-on-first-use + pinnable). DEEP_ROUTING_TABLE
# remains as a DERIVED VIEW of that table's deep rows (same
# (C, g_shard, mailbox, winner, source) tuples — bench's routing audits
# and the historical tests keep reading it) and route_deep_engine
# delegates to the unified resolution; tests/test_autotune.py pins the
# two equal over the full shape lattice.
from raft_kotlin_tpu.parallel import autotune as autotune_mod

DEEP_ROUTING_TABLE = autotune_mod.derived_deep_table()


def route_deep_engine(C: int, g_shard: int,
                      platform: Optional[str] = None,
                      mailbox: bool = False) -> str:
    """Pick the deep-log per-shard engine ("fc" | "batched" | "flat") for a
    (log capacity, per-shard lane width[, mailbox]) shape — since round 13
    a view of the unified tuning layer (parallel/autotune.resolve_plan):
    the measured winner at the exact pinned shape, else the nearest pinned
    shape in log-space within the config's mailbox class.

    `platform` (default: jax.default_backend()) carries the one surviving
    NON-perf constraint: XLA:CPU's compile of the batched gather/scatter
    program blows up at real deep widths (the round-2 observation
    _make_shardmap_xla_tick documents), so CPU meshes stay on the per-pair
    flat engine regardless of shape — a compile-feasibility guard, not a
    perf class (autotune.apply_guards). `mailbox=True` selects the mailbox
    crossover entries and is only meaningful for delay_lo >= 1
    (known-delivery): τ=0 mailbox configs are handled by the CALLER (a
    slot can be filled and delivered within one tick, so only
    "flat"/per-pair is valid there).
    """
    return autotune_mod.deep_engine(C, g_shard, platform=platform,
                                    mailbox=mailbox)


def rng_shardings(cfg: RaftConfig, mesh: Mesh):
    """NamedShardings for the make_rng(cfg) operand tuple, derived from its
    own eval_shape: any leaf whose LAST axis is group-sized shards on the
    flat mesh over that axis (the key grids, the scenario bank's (G,)
    channels); everything else replicates. THE one copy of the rng
    placement contract (make_sharded_run, the deep sharded runners, and
    the sharded fuzz farm).

    Placement is decided by SHAPE, not rank: the old rank-based mapping
    ({0: replicate, 1: shard, 2: shard-last}) was a single-device
    assumption — any rank-1 leaf that is not group-sized (a future bank
    table row, a raw-key pair) would have been sharded over an axis it
    cannot tile on a real mesh."""
    from raft_kotlin_tpu.ops.tick import make_rng

    rep = NamedSharding(mesh, P())
    G = cfg.n_groups

    def pick(s):
        if s.ndim and s.shape[-1] == G:
            return NamedSharding(
                mesh, P(*([None] * (s.ndim - 1)), ("dcn", "ici")))
        return rep

    shapes = jax.eval_shape(lambda: make_rng(cfg))
    return jax.tree_util.tree_map(pick, shapes)


# ---------------------------------------------------------------------------
# Collective-freedom (ISSUE 10): groups never communicate, so the sharded
# TICK must be collective-free — telemetry/monitor/window reductions and
# checkpoint I/O are the ONLY cross-device traffic, and they live OUTSIDE
# shard_map by construction. These checkers make that claim auditable.

# Explicit cross-shard communication primitives a shard_map body could
# contain (jaxpr names; psum2 is the newer psum binding).
COLLECTIVE_PRIMITIVES = frozenset({
    "psum", "psum2", "pmax", "pmin", "pmean", "ppermute", "pbroadcast",
    "all_gather", "all_gather_invariant", "all_to_all", "reduce_scatter",
    "pgather", "axis_index_groups",
})

# HLO instruction names XLA emits for cross-device traffic (compiled-module
# scan — catches what the SPMD partitioner inserts, which never appears in
# a jaxpr).
HLO_COLLECTIVE_OPS = ("all-reduce", "all-gather", "all-to-all",
                      "collective-permute", "reduce-scatter",
                      "collective-broadcast")


def jaxpr_collectives(fn, *args) -> list:
    """Names of every collective primitive reachable from fn's jaxpr
    (recursing through scan/cond/pjit/shard_map sub-jaxprs). Inside
    shard_map, ANY cross-device op must be an explicit collective
    primitive — so an empty list proves the traced program is shard-local
    end to end."""
    jaxpr = jax.make_jaxpr(fn)(*args)
    found = []

    def walk(jx):
        for eqn in jx.eqns:
            if eqn.primitive.name in COLLECTIVE_PRIMITIVES:
                found.append(eqn.primitive.name)
            for v in eqn.params.values():
                for sub in jax.tree_util.tree_leaves(
                        v, is_leaf=lambda x: hasattr(x, "jaxpr")):
                    if hasattr(sub, "jaxpr"):
                        walk(sub.jaxpr)
                    elif hasattr(sub, "eqns"):
                        walk(sub)

    walk(jaxpr.jaxpr)
    return found


def compiled_collectives(lowered_or_fn, *args) -> list:
    """HLO collective instruction names in the COMPILED module of a jitted
    callable (pass a jax.jit result plus its args, or an already-lowered
    object). This is the check that covers the SPMD ("xla" impl) path,
    where collectives are inserted at partitioning time and never appear
    in the jaxpr."""
    import re

    if hasattr(lowered_or_fn, "compile"):
        compiled = lowered_or_fn.compile()
    else:
        compiled = jax.jit(lowered_or_fn).lower(*args).compile()
    text = compiled.as_text()
    out = []
    # HLO spells ops as `%name = type op-name(...)`; on TPU/GPU backends
    # collectives routinely lower to ASYNC pairs (`all-reduce-start` /
    # `all-reduce-done`) — match those too and report the canonical name
    # (a matcher that only saw the sync form would false-pass a module
    # full of cross-device traffic). Anchored on `(` so instruction
    # spellings match, not metadata substrings.
    pats = [(op, re.compile(rf"(?:^|[\s=]){re.escape(op)}"
                            rf"(?:-start|-done)?\("))
            for op in HLO_COLLECTIVE_OPS]
    for line in text.splitlines():
        s = line.strip()
        for op, pat in pats:
            if pat.search(s):
                out.append(op)
    return out


def assert_tick_collective_free(cfg: RaftConfig, mesh: Mesh,
                                impl: str = "xla") -> int:
    """Trace ONE bare sharded tick (no observers — their reductions are
    the sanctioned cross-device traffic) and assert its jaxpr contains no
    collective primitive; returns the number of shard_map-visible
    collectives found (always 0 on success). The bench pod legs and
    tests/test_pod.py publish/pin this."""
    from raft_kotlin_tpu.ops.tick import make_rng

    if impl == "pallas":
        tick = _make_shardmap_pallas_tick(cfg, mesh)
    elif cfg.uses_dyn_log:
        tick = _make_shardmap_xla_tick(cfg, mesh)
    else:
        xla_tick = make_tick(cfg)
        tick = lambda st, rng: xla_tick(st, rng=rng)
    st = init_sharded(cfg, mesh)
    rng = jax.jit(lambda: make_rng(cfg),
                  out_shardings=rng_shardings(cfg, mesh))()
    found = jaxpr_collectives(tick, st, rng)
    assert not found, (
        f"sharded tick is NOT collective-free: {sorted(set(found))} — "
        "cross-device traffic outside the telemetry/checkpoint envelope")
    return len(found)


def make_mesh(devices: Optional[Sequence[jax.Device]] = None,
              dcn: Optional[int] = None) -> Mesh:
    """Build the canonical ("dcn", "ici") mesh over `devices` (default: all).

    `dcn` is the host-level axis size (default: number of distinct hosts among the
    devices, so a single-host run gets (1, n_chips) and a multi-host run gets
    (n_hosts, chips_per_host) with the ICI axis innermost — collectives that ride the
    inner axis stay on-chip interconnect).
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if dcn is None:
        dcn = len({d.process_index for d in devices}) or 1
    ici = len(devices) // dcn
    import numpy as np

    return Mesh(np.asarray(devices).reshape(dcn, ici), ("dcn", "ici"))


@functools.lru_cache(maxsize=1)
def _field_ndims() -> dict:
    """Per-field array rank, derived from init_state itself (eval_shape traces the
    init without allocating, with the §10 mailbox ON so every optional field has a
    shape) — new RaftState fields shard correctly on their last axis by
    construction."""
    shapes = jax.eval_shape(
        lambda: init_state(
            RaftConfig(n_groups=1, n_nodes=2, log_capacity=2, mailbox=True,
                       compact_watermark=1))
    )
    return {f.name: getattr(shapes, f.name).ndim for f in dataclasses.fields(RaftState)}


def state_sharding(mesh: Mesh, cfg: Optional[RaftConfig] = None) -> RaftState:
    """A RaftState-shaped pytree of NamedShardings: every array sharded over the
    flattened ("dcn", "ici") mesh on its LAST (groups) axis — state is groups-minor
    (models/state.py); rank-0 scalars (the tick counter) replicated. §10 mailbox
    fields get shardings only when `cfg.uses_mailbox` (None otherwise, matching the
    state pytree's structure)."""
    from raft_kotlin_tpu.models.state import MAILBOX_FIELDS, SNAPSHOT_FIELDS

    use_mail = cfg is not None and cfg.uses_mailbox
    use_cmp = cfg is not None and cfg.uses_compaction
    ndims = _field_ndims()
    fields = {}
    for f in dataclasses.fields(RaftState):
        if (f.name in MAILBOX_FIELDS and not use_mail) or (
                f.name in SNAPSHOT_FIELDS and not use_cmp):
            fields[f.name] = None
            continue
        nd = ndims[f.name]
        spec = P(*([None] * (nd - 1)), ("dcn", "ici")) if nd else P()
        fields[f.name] = NamedSharding(mesh, spec)
    return RaftState(**fields)


def pad_groups(cfg: RaftConfig, mesh: Mesh) -> RaftConfig:
    """Round n_groups up to a multiple of the mesh size (sharding needs equal shards;
    extra groups are real simulations, just surplus)."""
    m = math.prod(mesh.devices.shape)
    g = ((cfg.n_groups + m - 1) // m) * m
    return dataclasses.replace(cfg, n_groups=g)


def init_sharded(cfg: RaftConfig, mesh: Mesh) -> RaftState:
    """init_state with every array laid out per `state_sharding` from birth (no
    host-side materialize-then-scatter: jit with out_shardings computes each shard
    on its own device)."""
    sh = state_sharding(mesh, cfg)
    fn = jax.jit(lambda: init_state(cfg), out_shardings=sh)
    return fn()


def _make_shardmap_pallas_tick(cfg: RaftConfig, mesh: Mesh,
                               interpret: Optional[bool] = None,
                               fused_ticks: Optional[int] = 1,
                               telemetry: bool = False,
                               monitor: bool = False,
                               aux_source: str = "staged",
                               compute: str = "unpacked"):
    """The Pallas megakernel applied per device shard via jax.shard_map.

    Division of labor mirrors ops/pallas_tick.make_pallas_tick: the RNG/aux
    pre-pass and the deferred-draw post-pass stay ordinary (globally sharded) XLA
    ops; only the pure flat-state kernel runs inside shard_map, each device
    processing its own (rows, G/n_dev) lane slab. Zero collectives inside the tick.

    `fused_ticks` = T > 1 (ISSUE 7) builds the FUSED-T kernel per shard
    instead: the returned function advances T ticks per call and returns
    (state, overflow_count, per_tick_snapshots) — the aux/draw-table
    pre-pass stays globally-sharded XLA exactly like the 1-tick RNG
    pre-pass, so the kernel still needs no global group offsets. None =
    route_fused_ticks at the per-shard tile (1 on CPU meshes — the sticky
    fallback); a routed T that fails the fused VMEM model falls back to 1.
    `telemetry`/`monitor` make the fused kernel emit exactly the
    requested observers' per-tick snapshot set (fused_snapshot_fields —
    a telemetry-only run never pays the monitor's per-tick log blocks);
    make_sharded_run replays the T transitions from it, OUTSIDE shard_map
    as always. The resolved T is exposed as `tick.fused_ticks`.

    `aux_source` = "inkernel" (ISSUE 15, §17): the resident key-table /
    key-word operands are built OUTSIDE shard_map at global G (the ktab
    gidx row carries the GLOBAL group iota, so after the lanes sharding
    each shard's kernel derives global counter indices — the same bits as
    the unsharded run) and the make_aux / fused_launch_aux pre-passes
    disappear. Leader-isolation banks fuse on this path (the
    resolve_fused_geometry gate is aux_source-aware).

    `compute` = "packed" (ISSUE 16, §18): the per-shard kernel evaluates
    the phase lattice on packed peer/ctrl words — flat_to_packed_compute
    / packed_compute_to_flat wrap each shard_map call exactly like the
    single-device make_pallas_tick, OUTSIDE shard_map (elementwise over
    the lanes axis, so shard-local under the partitioner; zero new
    collectives). Packed word operands are lanes-minor rank-2 like every
    other plane, so the lanes sharding specs apply unchanged.
    """
    from raft_kotlin_tpu.ops import tick as tick_mod
    from raft_kotlin_tpu.ops.pallas_tick import (
        _TILES,
        COMPUTES,
        cast_flat_in,
        cast_flat_out,
        default_tile,
        flat_to_packed_compute,
        inkernel_aux_operands,
        inkernel_aux_statics,
        make_pallas_core,
        packed_compute_to_flat,
        route_ilp_subtiles,
    )

    inkernel = aux_source == "inkernel"
    if compute not in COMPUTES:
        raise ValueError(f"unknown compute {compute!r}")
    pc = compute == "packed"

    N, G = cfg.n_nodes, cfg.n_groups
    n_dev = math.prod(mesh.devices.shape)
    assert G % n_dev == 0, "pad_groups first"
    g_local = G // n_dev
    if interpret is None:
        # Resolve from the mesh's own devices: jax.default_backend() can report a
        # plugin backend even when this run targets the virtual CPU device pool.
        interpret = mesh.devices.flatten()[0].platform == "cpu"
    if interpret:
        tile = min(g_local, 256)
        if g_local % tile:
            tile = math.gcd(g_local, tile) or 1
    else:
        try:
            tile = default_tile(cfg, g_local, False, aux_source=aux_source,
                                compute=compute)
        except ValueError as e:
            raise ValueError(
                f"sharded pallas needs the PER-DEVICE shard ({g_local} = "
                f"n_groups // {n_dev} devices) lane-aligned and within VMEM: "
                f"choose n_groups as a multiple of n_dev * tile for a tile in "
                f"{_TILES} that fits the config, or use impl='xla'"
            ) from e
    # Per-shard sub-tile ILP (ISSUE 4): same measured-table routing as the
    # single-device kernel; interpret/CPU shards stay at K=1.
    platform = "cpu" if interpret else mesh.devices.flatten()[0].platform
    sub_k = route_ilp_subtiles(tile, platform)
    lanes_spec = P(None, ("dcn", "ici"))

    # Fused-T resolution (ISSUE 7) through THE shared resolution
    # (resolve_fused_geometry over the PER-SHARD lane width and the
    # mesh's own platform): route T by the per-shard tile, apply the
    # fused VMEM model (which may shrink the tile — the ILP K is
    # re-routed for the tile the kernel actually compiles with), routed-T
    # falls back sticky to 1, pinned-T raises.
    from raft_kotlin_tpu.ops.pallas_tick import (
        _snapshot_rows, fused_aux_slabs, fused_launch_aux,
        fused_snapshot_fields, resolve_fused_geometry,
        unpack_fused_outputs)

    snap_fields = (fused_snapshot_fields(cfg, telemetry=telemetry,
                                         monitor=monitor)
                   if (telemetry or monitor) else ())
    tile_f, sub_k_f, T_f = resolve_fused_geometry(
        cfg, interpret, fused_ticks=fused_ticks,
        snap_rows=_snapshot_rows(cfg, snap_fields),
        lanes=g_local, platform=platform, aux_source=aux_source,
        compute=compute)
    if T_f <= 1:
        snap_fields = ()
    if T_f > 1:
        build_call_f = make_pallas_core(cfg, g_local, tile_f, interpret,
                                        subtiles=sub_k_f, fused_ticks=T_f,
                                        tick_states=snap_fields,
                                        aux_source=aux_source,
                                        compute=compute)

        def tick_fused(state: RaftState, rng):
            base, tkeys, bkeys, scen = tick_mod.split_rng(rng)
            flat = tick_mod.flatten_state(cfg, state)
            if pc:
                flat = flat_to_packed_compute(cfg, flat)
            if inkernel:
                # Resident operands at GLOBAL G, sharded over lanes like
                # everything else — no aux pre-pass, no draw tables.
                stat = inkernel_aux_statics(cfg, base, tkeys, bkeys, scen)
                call, sfields, aux_names, snaps = build_call_f(
                    tick_mod.make_flags(cfg))
                ins = cast_flat_in(flat, {}, sfields, ()) \
                    + inkernel_aux_operands(stat, state.tick)
            else:
                # The aux/draw-table pre-pass is THE shared fused assembly
                # (fused_launch_aux/fused_aux_slabs — one copy of the
                # outside-the-kernel half of the bit-compat contract).
                per, flags, (el_tab, b_tab) = fused_launch_aux(
                    cfg, base, tkeys, bkeys, state.tick, state.t_ctr,
                    state.b_ctr, T_f, scen=scen)
                call, sfields, aux_names, snaps = build_call_f(flags)
                ins = cast_flat_in(flat, {}, sfields, ()) \
                    + fused_aux_slabs(per, aux_names) + [el_tab, b_tab]
            n_out = len(sfields) + 1 + T_f * len(snaps)
            shard_call = shard_map_compat(
                lambda *a: call(*a),
                mesh=mesh,
                in_specs=(lanes_spec,) * len(ins),
                out_specs=(lanes_spec,) * n_out,
                check_vma=False,
            )
            with telemetry_mod.engine_scope("shardmap-pallas-fused"):
                outs = shard_call(*ins)
            s2, ov, ticks_f = unpack_fused_outputs(
                list(outs), sfields, snaps, T_f)
            if pc:
                s2 = packed_compute_to_flat(cfg, s2)
                sfields = tuple(s2)
            s, _ = cast_flat_out(cfg, [s2[k] for k in sfields], sfields,
                                 with_dirty=False)
            new_state = RaftState(**tick_mod.unflatten_state(cfg, s),
                                  tick=state.tick + T_f)
            return new_state, jnp.sum(ov), ticks_f

        tick_fused.fused_ticks = T_f
        return tick_fused

    build_call = make_pallas_core(cfg, g_local, tile, interpret,
                                  subtiles=sub_k, aux_source=aux_source,
                                  compute=compute)

    def tick(state: RaftState, rng) -> RaftState:
        base, tkeys, bkeys, scen = tick_mod.split_rng(rng)
        flat = tick_mod.flatten_state(cfg, state)
        if pc:
            flat = flat_to_packed_compute(cfg, flat)
        if inkernel:
            stat = inkernel_aux_statics(cfg, base, tkeys, bkeys, scen)
            call, sfields, aux_names = build_call(tick_mod.make_flags(cfg))
            ins = cast_flat_in(flat, {}, sfields, ()) \
                + inkernel_aux_operands(stat, state.tick)
        else:
            aux, flags = tick_mod.make_aux(cfg, base, tkeys, bkeys, state,
                                           None, None, scen=scen)
            call, sfields, aux_names = build_call(flags)
            ins = cast_flat_in(flat, aux, sfields, aux_names)
        shard_call = shard_map_compat(
            lambda *a: call(*a),
            mesh=mesh,
            in_specs=(lanes_spec,) * len(ins),
            out_specs=lanes_spec,
            # pallas_call out_shapes carry no vma annotations; the kernel is
            # embarrassingly parallel over lanes, so the check adds nothing.
            check_vma=False,
        )
        with telemetry_mod.engine_scope("shardmap-pallas"):
            outs = shard_call(*ins)
        if pc:
            outs = list(outs)
            sdict = packed_compute_to_flat(
                cfg, dict(zip(sfields, outs[:len(sfields)])))
            sfields = tuple(sdict)
            outs = [sdict[k] for k in sfields] + [outs[-1]]
        s, el_dirty = cast_flat_out(cfg, outs, sfields)
        return tick_mod.finish_tick(
            cfg, tkeys, tick_mod.unflatten_state(cfg, s), el_dirty, state.tick)

    tick.fused_ticks = 1
    return tick


def _make_shardmap_xla_tick(cfg: RaftConfig, mesh: Mesh,
                            batched: Optional[bool] = None):
    """The XLA tick with phase_body applied per device shard via jax.shard_map
    (same division of labor as _make_shardmap_pallas_tick: RNG/aux pre-pass
    and deferred-draw post-pass stay globally-sharded XLA; the phase lattice
    runs shard-locally — it is embarrassingly parallel over groups, and
    phase_body reads its group count from the arrays, not the config).

    Used for deep-log (dyn) configs: XLA's SPMD partitioner mishandles the
    per-lane log gather/scatter program (observed on the CPU backend:
    pathological HLO-pass memory, then SIGABRT at execution — consistent
    with the gathers being rewritten into materialized dense forms).
    shard_map keeps the compiled per-shard program identical to the
    single-device one. Bit-identical either way.

    `batched` selects the per-shard engine: True = the BATCHED deep engine
    (the single-device fast path) per shard; False = the per-pair FLAT
    engine; None (default) = batched on accelerators, per-pair flat on CPU.
    The old always-flat routing was a TPU path decision made from a CPU
    failure (VERDICT r04 weak #3): the CPU blowup lives in XLA:CPU's
    compile of the batched gather/scatter program itself, so CPU keeps the
    flat engine, while TPU shards now run the same engine the single-device
    config-5 stage uses (shard_map bypasses the SPMD partitioner; the
    round-5 on-chip A/B lives in BENCH_r05.json shardeddeep_* fields)."""
    from raft_kotlin_tpu.ops import tick as tick_mod

    n_dev = math.prod(mesh.devices.shape)
    assert cfg.n_groups % n_dev == 0, "pad_groups first"
    lanes_spec = P(None, ("dcn", "ici"))
    if cfg.uses_mailbox and not cfg.known_delivery:
        # τ=0 mailbox: a slot can be filled and delivered within one tick,
        # so no pre-computable read set exists — per-pair FLAT regardless
        # of what the caller pinned (make_flags enforces the same rule).
        batched = False
    if batched is None:
        # Route by SHAPE through the measured crossover table
        # (route_deep_engine, r6; mailbox dimension r7 — for delay_lo >= 1
        # the known-delivery batched engine runs under the mailbox too).
        # "fc" collapses to batched here because this per-tick API carries
        # no cache state (multi-tick fc runs live in
        # ops/deep_cache.make_sharded_deep_scan, which routes itself).
        batched = route_deep_engine(
            cfg.phys_capacity, cfg.n_groups // n_dev,
            mesh.devices.flatten()[0].platform,
            mailbox=cfg.uses_mailbox) != "flat"
    batched_arg: Optional[bool] = None if batched else False

    def tick(state: RaftState, rng) -> RaftState:
        base, tkeys, bkeys, scen = tick_mod.split_rng(rng)
        aux, flags = tick_mod.make_aux(cfg, base, tkeys, bkeys, state,
                                       None, None, batched=batched_arg,
                                       sharded=not batched, scen=scen)
        sfields = tick_mod.state_fields(flags)
        aux_names = tuple(k for k in tick_mod.AUX_FIELDS if k in aux)
        flat = tick_mod.flatten_state(cfg, state)

        def body(*arrs):
            s = dict(zip(sfields, arrs[: len(sfields)]))
            a = dict(zip(aux_names, arrs[len(sfields):]))
            el_dirty = tick_mod.phase_body(cfg, s, a, flags)
            return tuple(s[k] for k in sfields) + (el_dirty,)

        ins = [flat[k] for k in sfields] + [aux[k] for k in aux_names]
        with telemetry_mod.engine_scope("shardmap-xla"):
            outs = shard_map_compat(
                body, mesh=mesh,
                in_specs=(lanes_spec,) * len(ins),
                out_specs=(lanes_spec,) * (len(sfields) + 1),
                check_vma=False,
            )(*ins)
        s = dict(zip(sfields, outs[:-1]))
        return tick_mod.finish_tick(
            cfg, tkeys, tick_mod.unflatten_state(cfg, s), outs[-1], state.tick)

    return tick


def make_sharded_run(cfg: RaftConfig, mesh: Mesh, n_ticks: int,
                     metrics_every: int = 0, impl: str = "xla",
                     telemetry: bool = False, monitor: bool = False,
                     fused_ticks: Optional[int] = None,
                     layout: str = "wide", aux_source: str = "staged",
                     compute: str = "unpacked", serving: bool = False):
    """Compile run(state [, inject]) -> (state, metrics) sharded over `mesh`.

    metrics: dict of cross-group reductions emitted every `metrics_every` ticks
    — each a (n_ticks // metrics_every,) array with one row per window:
    `leaders` (groups with ≥1 leader, sampled at the window's last tick),
    `elections` (vote-round starts summed over the window — the rounds-delta
    telescopes, so no per-tick accumulator is carried), `commit_total` (sum
    over groups of max node commit, sampled at the window's last tick). These
    are the only cross-device ops (XLA inserts the reductions over ICI/DCN).
    metrics_every=0 keeps even those out and returns (state, None);
    metrics_every=1 is the dense per-tick trace. Trailing n_ticks %
    metrics_every ticks still run, after the last emitted row.

    impl: "xla" (default — the SPMD partitioner splits the tick shard-locally) or
    "pallas" (the megakernel per shard via shard_map).

    telemetry=True threads the scan-carry flight recorder
    (utils/telemetry.py) through the run; monitor=True threads the
    scan-carry safety-invariant monitor (Figure-3 checks + latch + history
    ring, finalized form, replicated out). The return grows accordingly:
    (state, metrics[, telemetry][, monitor]). Both run their reductions on
    the globally-sharded states OUTSIDE shard_map (the same collective
    class as the window metrics; zero per-tick host traffic, read back
    once) — latch group indices are therefore GLOBAL. Protocol bits are
    unchanged.

    `fused_ticks` (impl="pallas" only; ISSUE 7): T ticks fused per kernel
    launch per shard (_make_shardmap_pallas_tick) — the sharded headline
    pays one launch per T-block. None = route_fused_ticks at the
    per-shard tile (1 on CPU meshes). Sticky T=1 fallbacks: metrics
    windows that don't tile into T-blocks (metrics_every % T != 0) and
    runs shorter than T. Telemetry/monitor replay the fused kernel's
    per-tick snapshots between launches (same reductions, outside
    shard_map — bit-equal to the unfused run); the fused kernel's
    draw-table overflow flag is summed across the run and host-checked
    after each call (RuntimeError on violation, the loud-failure
    contract).

    `layout`="packed" (ISSUE 11) carries the packed state layout
    (models/state.pack_state — SEMANTICS.md §14) through the sharded scan:
    pack/unpack run OUTSIDE shard_map on the globally sharded state
    (elementwise, shard-local under the partitioner — the per-shard tick
    program is untouched and stays collective-free; only the width-latch
    reduction joins the observers' collective class). External contract
    unchanged (wide in, wide out); the latch is host-checked per call.

    `aux_source`="inkernel" (impl="pallas" only; ISSUE 15) draws the
    per-tick aux set inside the kernel from resident counter tables
    instead of staging it through HBM — see _make_shardmap_pallas_tick.
    Sticky T=1 fallbacks above still apply, but the in-kernel path keeps
    its aux contract at any T (the fallback rebuild threads aux_source
    too).

    `serving`=True (SEMANTICS.md §20; needs cfg.serve_slots > 0) threads
    the scan-carry serving state (ops/serving.py — applied KV planes,
    latency histograms, read gating) through the run, advanced on the
    globally-sharded post-tick states OUTSIDE shard_map exactly like the
    monitor; the return grows a trailing serving carry (replicated out).
    The per-group planes stay shard-local; only the latency-histogram
    bumps join the observers' collective class, and those are
    order-independent int sums — the histograms are BIT-IDENTICAL to the
    single-device run. Fused T-blocks take the sticky T=1 fallback under
    serving (the per-tick apply fold needs per-tick states).

    `compute`="packed" (ISSUE 16, §18) evaluates the phase lattice on
    packed peer/ctrl words inside the per-shard kernel (impl="pallas")
    or the XLA packed-compute twin (impl="xla", non-deep) — bit-equal to
    unpacked by construction. Requires layout="packed" (the §18 pairing:
    packed compute only ships with the packed carry); the flat↔packed
    conversions run OUTSIDE shard_map on lanes-minor planes, so the tick
    stays collective-free. Deep-log (dyn) configs route through
    _make_shardmap_xla_tick, which has no packed twin — refused loudly.
    """
    from raft_kotlin_tpu.models.state import (
        check_packed_ov, pack_state, unpack_state)
    from raft_kotlin_tpu.ops.tick import flatten_state, make_rng, split_rng
    from raft_kotlin_tpu.ops import serving as serving_mod
    from raft_kotlin_tpu.utils import rng as rngmod

    packed = layout == "packed"
    if layout not in ("wide", "packed"):
        raise ValueError(f"unknown layout {layout!r}")
    if aux_source not in ("staged", "inkernel"):
        raise ValueError(f"unknown aux_source {aux_source!r}")
    if aux_source == "inkernel" and impl != "pallas":
        raise ValueError("aux_source='inkernel' requires impl='pallas'")
    if compute not in ("unpacked", "packed"):
        raise ValueError(f"unknown compute {compute!r}")
    if compute == "packed" and layout != "packed":
        raise ValueError(
            "compute='packed' requires layout='packed' (§18: packed-domain "
            "compute only ships with the packed carry — autotune pairs them)")
    if compute == "packed" and impl != "pallas" and cfg.uses_dyn_log:
        raise ValueError(
            "compute='packed' has no deep-log XLA shard twin; plans for "
            "dyn-log configs are stamped compute='unpacked'")
    if serving and not serving_mod.serving_enabled(cfg):
        raise ValueError("serving needs cfg.serve_slots > 0")

    fused_block, T_f = None, 1
    if impl == "pallas":
        cand = _make_shardmap_pallas_tick(cfg, mesh, fused_ticks=fused_ticks,
                                          telemetry=telemetry,
                                          monitor=monitor,
                                          aux_source=aux_source,
                                          compute=compute)
        T_f = getattr(cand, "fused_ticks", 1)
        if T_f > 1 and ((metrics_every and metrics_every % T_f)
                        or n_ticks < T_f or serving):
            # sticky fallback: windows/run must tile into T-blocks; the
            # §20 serving fold needs per-tick states (replaying fused
            # snapshots here would also need SERVING_STATE_FIELDS staged
            # through the shard-map kernel — per-tick launches instead).
            T_f = 1
        elif T_f > 1:
            fused_block = cand
        if T_f == 1:
            shardmap_tick = cand if getattr(cand, "fused_ticks", 1) == 1 \
                else _make_shardmap_pallas_tick(cfg, mesh,
                                                aux_source=aux_source,
                                                compute=compute)
        else:
            shardmap_tick = _make_shardmap_pallas_tick(cfg, mesh,
                                                       aux_source=aux_source,
                                                       compute=compute)
        tick_fn = lambda st, rng: shardmap_tick(st, rng)
    elif cfg.uses_dyn_log:
        # Deep-log (dyn) configs: phase_body per shard — the SPMD
        # partitioner mishandles the per-lane gather/scatter program (see
        # _make_shardmap_xla_tick; round 5 routes accelerator shards to
        # the BATCHED engine). For multi-TICK deep runs, the faster path
        # is ops/deep_cache.make_sharded_deep_scan (the frontier-cache
        # engine per shard) — it carries cache state across ticks, which
        # this per-tick API cannot.
        shardmap_tick = _make_shardmap_xla_tick(cfg, mesh)
        tick_fn = lambda st, rng: shardmap_tick(st, rng)
    else:
        xla_tick = make_tick(cfg, compute=compute)
        tick_fn = lambda st, rng: xla_tick(st, rng=rng)
    sh = state_sharding(mesh, cfg)
    rep = NamedSharding(mesh, P())
    # rng operand shardings: base key replicated; (N, G) key grids sharded
    # on the groups axis like every state array; scenario-bank (G,) arrays
    # (when cfg.scenario) sharded over groups (rng_shardings).
    rng_sh = rng_shardings(cfg, mesh)
    # rng computed straight into its mesh placement (init_sharded's pattern):
    # a host-side make_rng + device_put to these shardings would raise on a
    # multi-process mesh, where the shardings span non-addressable devices
    # (tests/test_multiprocess.py exercises exactly this). The tiny producer
    # program bakes the seed, but the SCAN below still takes rng as an
    # operand, so the expensive compilation stays seed-independent.
    rng_placed = jax.jit(lambda: make_rng(cfg), out_shardings=rng_sh)()

    def _rounds_sum(st):
        # Absolute int32 round counters summed over all N*G lanes can exceed
        # int32 on long production-scale soaks (unlike the old per-tick delta
        # sum) — widen like commit_total when x64 is available.
        r = st.rounds
        return jnp.sum(r.astype(jnp.int64) if jax.config.jax_enable_x64 else r)

    def window_metrics(st, rounds0):
        return {
            "leaders": jnp.sum(
                jnp.any((st.role == LEADER) & st.up, axis=0).astype(jnp.int32)
            ),
            # Elections = vote-round starts (rounds-delta) — the ONE canonical
            # definition, shared with utils.metrics.tick_metrics and bench.py.
            # (Role-transition counting would miss consecutive rounds by a node
            # that stays CANDIDATE through backoff loops — the churn case.)
            "elections": _rounds_sum(st) - rounds0,
            "commit_total": jnp.sum(jnp.max(st.commit, axis=0).astype(
                jnp.int64 if jax.config.jax_enable_x64 else jnp.int32)),
        }

    def _wide(st):
        return unpack_state(cfg, st) if packed else st

    def _pack(st, ms, tel, mon, srv=None):
        # One scalar reduction of the (G,) per-group latch, at scan exit
        # only — the per-tick carry stays lane-shaped/shard-local, so the
        # packed sharded tick adds NO per-tick collective.
        pov = jnp.any(st.ov != 0) if packed else None
        st = _wide(st)
        out = (st, ms)
        if telemetry:
            out = out + (tel,)
        if monitor:
            out = out + (telemetry_mod.monitor_finalize(mon),)
        if serving:
            out = out + (srv,)
        if packed:
            out = out + (pov,)
        return out

    def run(st, rng):
        if packed:
            st = pack_state(cfg, st)
        if serving:
            base_k, _tk, _bk, scen_b = split_rng(rng)
            srv_kw = rngmod.kt_key_words(base_k)
        else:
            srv_kw = scen_b = None

        def one(carry, _):
            s, tel, mon, srv = carry
            w = _wide(s)
            s2 = tick_fn(w, rng)
            if tel is not None:
                tel = telemetry_mod.telemetry_step(w, s2, tel)
            srv_prev = srv
            if srv is not None:
                # Serving advances BEFORE the monitor folds so the §21
                # srv_* series columns see this tick's serving pair.
                srv = serving_mod.serving_step(
                    cfg, serving_mod.serving_view(s2), srv, kw=srv_kw,
                    scen=scen_b)
            if mon is not None:
                mon = telemetry_mod.monitor_step(w, s2, mon,
                                                 srv_prev=srv_prev,
                                                 srv_cur=srv)
            nxt = pack_state(cfg, s2, ov=s.ov) if packed else s2
            return (nxt, tel, mon, srv), None

        tel0 = telemetry_mod.telemetry_zeros() if telemetry else None
        mon0 = telemetry_mod.monitor_init(cfg.n_groups, n_ticks, monitor,
                                          **telemetry_mod.ops_kw(cfg))
        srv0 = serving_mod.serving_init(cfg) if serving else None
        if not metrics_every:
            (st, tel, mon, srv), _ = jax.lax.scan(
                one, (st, tel0, mon0, srv0), None, length=n_ticks)
            return _pack(st, None, tel, mon, srv)

        def win(carry, _):
            st, tel, mon, srv = carry
            rounds0 = _rounds_sum(_wide(st))
            (st, tel, mon, srv), _ = jax.lax.scan(
                one, (st, tel, mon, srv), None, length=metrics_every)
            return (st, tel, mon, srv), window_metrics(_wide(st), rounds0)

        (st, tel, mon, srv), ms = jax.lax.scan(
            win, (st, tel0, mon0, srv0), None,
            length=n_ticks // metrics_every)
        if n_ticks % metrics_every:
            (st, tel, mon, srv), _ = jax.lax.scan(
                one, (st, tel, mon, srv), None,
                length=n_ticks % metrics_every)
        return _pack(st, ms, tel, mon, srv)

    def run_fused(st, rng):
        # The fused-T variant (ISSUE 7): full T-blocks through the fused
        # per-shard kernel, remainder ticks through the 1-tick path; the
        # recorder/monitor replay the kernel's per-tick snapshots between
        # launches (fused_observe — the same step reductions, outside
        # shard_map, so latch group ids stay global and bits stay equal
        # to the unfused run). Returns _pack(...) + (overflow_total,);
        # the wrapper below host-checks and strips the overflow.
        # DELIBERATELY a sibling of run(), not a parameterization of it:
        # the T=1 sharded runner above is the production path of every
        # prior round and stays textually untouched; the fused suite
        # (tests/test_fused_ticks.py) pins the two bit-equal.
        from raft_kotlin_tpu.ops.pallas_tick import fused_observe

        def one(carry, _):
            s, tel, mon = carry
            w = _wide(s)
            s2 = tick_fn(w, rng)
            if tel is not None:
                tel = telemetry_mod.telemetry_step(w, s2, tel)
            if mon is not None:
                mon = telemetry_mod.monitor_step(w, s2, mon)
            nxt = pack_state(cfg, s2, ov=s.ov) if packed else s2
            return (nxt, tel, mon), None

        def oneblock(carry, _):
            s, tel, mon = carry
            w = _wide(s)
            s2, ov, ticks_f = fused_block(w, rng)
            if tel is not None or mon is not None:
                tel, mon, _ = fused_observe(cfg, flatten_state(cfg, w),
                                            ticks_f, tel, mon)
            nxt = pack_state(cfg, s2, ov=s.ov) if packed else s2
            return (nxt, tel, mon), ov

        def steps(carry, k):
            ov = jnp.zeros((), jnp.int32)
            nb, r = divmod(k, T_f)
            if nb:
                carry, ovs = jax.lax.scan(oneblock, carry, None, length=nb)
                ov = ov + jnp.sum(ovs)
            if r:
                carry, _ = jax.lax.scan(one, carry, None, length=r)
            return carry, ov

        tel0 = telemetry_mod.telemetry_zeros() if telemetry else None
        mon0 = telemetry_mod.monitor_init(cfg.n_groups, n_ticks, monitor,
                                          **telemetry_mod.ops_kw(cfg))
        if packed:
            st = pack_state(cfg, st)
        if not metrics_every:
            (st, tel, mon), ov = steps((st, tel0, mon0), n_ticks)
            return _pack(st, None, tel, mon) + (ov,)

        def win(carry, _):
            s, tel, mon = carry
            rounds0 = _rounds_sum(_wide(s))
            carry, ov = steps(carry, metrics_every)
            return carry, (window_metrics(_wide(carry[0]), rounds0), ov)

        carry, (ms, ovs) = jax.lax.scan(win, (st, tel0, mon0), None,
                                        length=n_ticks // metrics_every)
        ov = jnp.sum(ovs)
        if n_ticks % metrics_every:
            carry, ov2 = steps(carry, n_ticks % metrics_every)
            ov = ov + ov2
        st, tel, mon = carry
        return _pack(st, ms, tel, mon) + (ov,)

    out_sh = ((sh, rep if metrics_every else None)
              + ((rep,) if telemetry else ())
              + ((rep,) if monitor else ())
              + ((rep,) if serving else ())
              + ((rep,) if packed else ()))
    if T_f > 1:
        jitted_f = jax.jit(run_fused, in_shardings=(sh, rng_sh),
                           out_shardings=out_sh + (rep,))

        def call(st):
            res = jitted_f(st, rng_placed)
            res, ov = res[:-1], res[-1]
            if int(jax.device_get(ov)):
                raise RuntimeError(
                    f"fused-tick kernel draw-table overflow inside the "
                    f"sharded run (T={T_f}): the launch's draws were "
                    f"clamped and its bits are INVALID; results discarded")
            if packed:
                res, pov = res[:-1], res[-1]
                check_packed_ov(pov)
            return res

        return call
    jitted = jax.jit(run, in_shardings=(sh, rng_sh), out_shardings=out_sh)
    if packed:
        def call_packed(st):
            res = jitted(st, rng_placed)
            res, pov = res[:-1], res[-1]
            check_packed_ov(pov)
            return res

        return call_packed
    return lambda st: jitted(st, rng_placed)
