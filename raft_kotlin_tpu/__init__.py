"""raft_kotlin_tpu — a TPU-native, vectorized many-group Raft simulation framework.

Built from scratch against the capabilities of arodionov/raft-kotlin (see SURVEY.md):
the reference's single-group node state machine (elections, RequestVote/AppendEntries,
log matching, commit advancement — reference RaftServer.kt, Commons.kt) is re-designed
as pure, `jax.jit`-compiled batched ops stepping all (groups x nodes) in lockstep, with
a deterministic scalar CPU oracle as the correctness reference: TPU traces must
bit-match it (SEMANTICS.md is the shared normative spec).

Layout:
  models/    CPU oracle, batched state schema, simulator driver
  ops/       vectorized tick kernels (vote/append decision tables, timers, log ops)
  parallel/  device-mesh sharding, collectives, checkpoint/resume
  utils/     config, canonical RNG, tracing/metrics
  api/       client-facing command API (HTTP parity with the reference's ktor server)
"""

from raft_kotlin_tpu.utils.config import RaftConfig

__version__ = "0.1.0"
__all__ = ["RaftConfig"]
