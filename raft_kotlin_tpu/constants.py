"""Shared spec constants: role and round-state encodings (SEMANTICS.md §2, §5).

Single source of truth for both the scalar oracle and the vectorized kernel — these
values are part of the trace format the differential tests compare bit-for-bit.
Roles mirror the reference's `enum class State` ordinal order (RaftServer.kt:24-26).
"""

FOLLOWER, CANDIDATE, LEADER = 0, 1, 2
IDLE, BACKOFF, ACTIVE = 0, 1, 2
