"""ctypes binding for the native C++ reference simulator (native/raft_oracle.cpp).

The C++ engine implements the same SEMANTICS.md tick machine as the Python oracle and
the JAX kernel, but is pure integer logic: all randomness (counted timeout/backoff
draws, §4 iid edge masks, §9 fault-event masks) is pre-drawn HERE through the canonical
`utils/rng.py` derivation and handed over as flat tables, so all three implementations
are bit-identical by construction. Use this one for large-G differential sweeps — it
steps thousands of groups per second per core where the Python oracle does tens.

Build: `g++ -O2 -shared -fPIC` at first use (cached next to the source, rebuilt when
the .cpp is newer). No pybind11 — plain C ABI + ctypes.
"""

from __future__ import annotations

import ctypes as ct
import dataclasses
import os
import subprocess
import threading
from typing import Dict, Optional

import numpy as np

from raft_kotlin_tpu.utils import rng as rngmod
from raft_kotlin_tpu.utils.config import RaftConfig

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "raft_oracle.cpp")
_LIB = os.path.join(_REPO_ROOT, "native", "libraft_oracle.so")
_BUILD_LOCK = threading.Lock()

_I32P = ct.POINTER(ct.c_int32)
_U8P = ct.POINTER(ct.c_uint8)


class _Dims(ct.Structure):
    _fields_ = [(k, ct.c_int32) for k in (
        "G", "N", "C", "hb_ticks", "round_ticks", "retry_ticks", "majority",
        "cmd_period", "cmd_node", "t0", "T", "Kt", "Kb",
        "delay_lo", "delay_hi", "mailbox",
        "compact_watermark", "compact_chunk", "ring_capacity")]


_STATE_FIELDS_I32 = (
    "term", "voted_for", "role", "commit", "last_index", "phys_len",
    "log_term", "log_cmd", "el_left", "round_state", "round_left", "round_age",
    "votes", "responses", "bo_left", "next_index", "match_index", "hb_left",
    "t_ctr", "b_ctr", "rounds", "snap_index", "snap_term", "snap_digest",
    "cap_ov",
)
_STATE_FIELDS_U8 = ("el_armed", "responded", "hb_armed", "up", "link_up")

_MAILBOX_ORDER = (
    "vq_due", "vq_term", "vq_lli", "vq_llt", "vq_round",
    "aq_due", "aq_term", "aq_pli", "aq_plt", "aq_hase", "aq_ent_t", "aq_ent_c",
    "aq_commit",
)

# Must mirror struct State's member ORDER in raft_oracle.cpp exactly.
_STATE_ORDER = (
    ("term", _I32P), ("voted_for", _I32P), ("role", _I32P), ("commit", _I32P),
    ("last_index", _I32P), ("phys_len", _I32P),
    ("log_term", _I32P), ("log_cmd", _I32P),
    ("el_armed", _U8P), ("el_left", _I32P),
    ("round_state", _I32P), ("round_left", _I32P), ("round_age", _I32P),
    ("votes", _I32P), ("responses", _I32P), ("responded", _U8P),
    ("bo_left", _I32P),
    ("next_index", _I32P), ("match_index", _I32P),
    ("hb_armed", _U8P), ("hb_left", _I32P),
    ("up", _U8P), ("link_up", _U8P),
    ("t_ctr", _I32P), ("b_ctr", _I32P), ("rounds", _I32P),
) + tuple((k, _I32P) for k in _MAILBOX_ORDER) + (
    # §15 (abi v4): snapshot state (null unless cfg.uses_compaction) +
    # the always-present capacity-exhaustion latch.
    ("snap_index", _I32P), ("snap_term", _I32P), ("snap_digest", _I32P),
    ("cap_ov", _I32P),
)


class _State(ct.Structure):
    _fields_ = list(_STATE_ORDER)


class _Inputs(ct.Structure):
    _fields_ = [
        ("timeout_draws", _I32P), ("backoff_draws", _I32P),
        ("edge_ok", _U8P), ("crash_m", _U8P), ("restart_m", _U8P),
        ("link_fail", _U8P), ("link_heal", _U8P),
        ("inject", _I32P), ("fault_cmd", _U8P), ("delay", _I32P),
        # §12 leader-isolation partition windows: [T][G] u8, 1 = every edge
        # touching a node that was a live leader at tick start is down this
        # tick (the one scenario channel that cannot be precomputed into
        # edge_ok — it depends on per-tick state the engine itself holds).
        ("leader_iso", _U8P),
    ]


class _Trace(ct.Structure):
    _fields_ = [(k, _I32P) for k in (
        "role", "term", "commit", "last_index", "voted_for", "rounds", "up")]


TRACE_FIELDS = tuple(k for k, _ in _Trace._fields_)


def trace_parity(ktr, ntr):
    """Compare a kernel trace (dict of (T, N, G) groups-minor arrays) against
    a NativeOracle trace (dict of (T, G, N) int32): returns
    (ok: (G,) bool — per-group bit-match over ALL TRACE_FIELDS,
     first_mismatch: str | None — field/tick/group/node of the first
     divergence, for diagnostics). The single canonical compare shared by
    bench.py's parity stage and the parity tests."""
    ok = None
    first = None
    for k in TRACE_FIELDS:
        kv = np.asarray(ktr[k]).transpose(0, 2, 1).astype(np.int32)  # (T,G,N)
        eq = kv == ntr[k]
        if ok is None:
            ok = np.ones(eq.shape[1], dtype=bool)
        ok &= np.all(eq, axis=(0, 2))
        if first is None and not eq.all():
            ti, g, n = np.argwhere(~eq)[0]
            first = (f"field {k} diverges first at tick={ti} group={g} "
                     f"node={n + 1}: kernel={kv[ti, g]} native={ntr[k][ti, g]}")
    return ok, first


def build_lib(force: bool = False) -> str:
    """Compile the shared library if missing or stale; returns its path."""
    with _BUILD_LOCK:
        if (not force and os.path.exists(_LIB)
                and os.path.getmtime(_LIB) >= os.path.getmtime(_SRC)):
            return _LIB
        tmp = _LIB + ".tmp"
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-o", tmp, _SRC],
            check=True, capture_output=True, text=True,
        )
        os.replace(tmp, _LIB)
        return _LIB


_lib_handle = None


def _lib() -> ct.CDLL:
    global _lib_handle
    if _lib_handle is None:
        lib = ct.CDLL(build_lib())
        lib.raft_run.restype = ct.c_int
        lib.raft_run.argtypes = [
            ct.POINTER(_Dims), ct.POINTER(_State), ct.POINTER(_Inputs),
            ct.POINTER(_Trace),
        ]
        assert lib.raft_abi_version() == 5
        _lib_handle = lib
    return _lib_handle


def _ptr(arr: Optional[np.ndarray], typ):
    if arr is None:
        return ct.cast(None, typ)
    return arr.ctypes.data_as(typ)


def _draw_tables(cfg: RaftConfig, kind: int, K: int, lo: int, hi: int) -> np.ndarray:
    """(G, N, K) int32 of the first K counted draws per (group, node) — the canonical
    §4 derivation, computed in one jitted JAX call."""
    import jax
    import jax.numpy as jnp

    base = rngmod.base_key(cfg.seed)
    keys = rngmod.grid_keys(base, kind, cfg.n_groups, cfg.n_nodes)

    @jax.jit
    def draw():
        f = lambda c: rngmod.draw_uniform_keyed(
            keys, jnp.full((cfg.n_groups, cfg.n_nodes), c, jnp.int32), lo, hi
        )
        out = jax.lax.map(f, jnp.arange(K, dtype=jnp.int32))  # (K, G, N)
        return jnp.transpose(out, (1, 2, 0))

    return np.ascontiguousarray(np.asarray(draw(), dtype=np.int32))


def _tick_masks(cfg: RaftConfig, t0: int, T: int) -> Dict[str, Optional[np.ndarray]]:
    """Per-tick §4/§9/§12 masks for ticks [t0, t0+T), shaped (T, ...); None
    when off. Scenario banks (cfg.scenario) route their per-group threshold
    channels through the same shared rng helpers, and tick-scheduled
    partition programs (split/asym — everything except leader isolation)
    fold into edge_ok up front; leader-isolation windows ride the separate
    (T, G) leader_iso channel the C++ engine evaluates against its own
    pre-phase-F roles."""
    import jax
    import jax.numpy as jnp

    base = rngmod.base_key(cfg.seed)
    G, N = cfg.n_groups, cfg.n_nodes
    ticks = jnp.arange(t0, t0 + T, dtype=jnp.int32)
    scen = {}
    if cfg.scenario is not None:
        if cfg.scenario.timeout_windows:
            raise NotImplementedError(
                "per-group election-timeout windows (§19 timeout_windows) "
                "are XLA-engine-only: the native engine's timeout tables "
                "bake the scalar cfg.el_lo/el_hi window")
        from raft_kotlin_tpu.models.oracle import scenario_bank_np

        scen = scenario_bank_np(cfg)

    def stack(fn):
        return np.ascontiguousarray(
            np.asarray(jax.jit(lambda: jax.lax.map(fn, ticks))(), dtype=np.uint8)
        )

    out: Dict[str, Optional[np.ndarray]] = {
        "edge_ok": None, "crash_m": None, "restart_m": None,
        "link_fail": None, "link_heal": None, "delay": None,
        "leader_iso": None,
    }
    if cfg.uses_mailbox and cfg.delay_lo < cfg.delay_hi:
        lo_g = jnp.asarray(scen["delay_lo"]) if "delay_lo" in scen else None
        hi_g = jnp.asarray(scen["delay_hi"]) if "delay_hi" in scen else None
        out["delay"] = np.ascontiguousarray(np.asarray(
            jax.jit(lambda: jax.lax.map(
                lambda t: rngmod.delay_mask(base, t, (G, N, N),
                                            cfg.delay_lo, cfg.delay_hi,
                                            lo_g=lo_g, hi_g=hi_g),
                ticks))(), dtype=np.int32))
    has_parts = "part_kind" in scen
    if cfg.p_drop > 0 or "drop_t" in scen or has_parts:
        drop_t = jnp.asarray(scen["drop_t"]) if "drop_t" in scen else None

        def edge_fn(t):
            e = rngmod.edge_ok_mask(base, t, (G, N, N), cfg.p_drop,
                                    thresh=drop_t)
            if has_parts:
                # Tick-scheduled programs fold here; leader-isolation
                # groups contribute nothing (leader_gn=None) and route
                # through the leader_iso channel below instead.
                e = e & ~rngmod.scenario_link_down(scen, t, None, N)
            return e

        out["edge_ok"] = stack(edge_fn)
    if has_parts:
        from raft_kotlin_tpu.utils.config import PART_LEADER

        if bool(np.any(scen["part_kind"] == PART_LEADER)):
            # The SAME §12 flapping-window formula as scenario_link_down
            # (rng.scenario_active), evaluated for all T ticks at once.
            act = rngmod.scenario_active(
                scen, np.arange(t0, t0 + T)[:, None])
            out["leader_iso"] = np.ascontiguousarray(
                (act & (scen["part_kind"][None] == PART_LEADER))
                .astype(np.uint8))
    warmup = cfg.scenario is not None and cfg.scenario.warmup_down > 0
    if cfg.p_crash > 0 or cfg.p_restart > 0 or "crash_t" in scen \
            or "restart_t" in scen or warmup:
        crash_t = jnp.asarray(scen["crash_t"]) if "crash_t" in scen else None
        restart_t = jnp.asarray(scen["restart_t"]) \
            if "restart_t" in scen else None

        def _fault_pair(t):
            # §15 warmup-down rides the same deterministic post-processing
            # as the kernels (utils/rng.apply_warmup_faults).
            crash = rngmod.event_mask(base, rngmod.KIND_CRASH, t, (G, N),
                                      cfg.p_crash, thresh=crash_t)
            restart = rngmod.event_mask(base, rngmod.KIND_RESTART, t,
                                        (G, N), cfg.p_restart,
                                        thresh=restart_t)
            return rngmod.apply_warmup_faults(
                cfg.scenario, cfg.cmd_node, t, crash, restart)

        # One stacked pass for BOTH masks (each _fault_pair call computes
        # the crash AND restart draws — mapping it twice doubled the work).
        crash_m, restart_m = jax.jit(
            lambda: jax.lax.map(_fault_pair, ticks))()
        out["crash_m"] = np.ascontiguousarray(
            np.asarray(crash_m, dtype=np.uint8))
        out["restart_m"] = np.ascontiguousarray(
            np.asarray(restart_m, dtype=np.uint8))
    if cfg.p_link_fail > 0 or cfg.p_link_heal > 0 or "link_fail_t" in scen \
            or "link_heal_t" in scen:
        lf_t = jnp.asarray(scen["link_fail_t"]) \
            if "link_fail_t" in scen else None
        lh_t = jnp.asarray(scen["link_heal_t"]) \
            if "link_heal_t" in scen else None
        out["link_fail"] = stack(
            lambda t: rngmod.event_mask(base, rngmod.KIND_LINK_FAIL, t,
                                        (G, N, N), cfg.p_link_fail,
                                        thresh=lf_t))
        out["link_heal"] = stack(
            lambda t: rngmod.event_mask(base, rngmod.KIND_LINK_HEAL, t,
                                        (G, N, N), cfg.p_link_heal,
                                        thresh=lh_t))
    return out


class NativeOracle:
    """All-groups scalar simulation in C++; same trace contract as the JAX kernel's
    make_run(trace=True) and the Python OracleGroup (bit-identical, SEMANTICS.md)."""

    def __init__(self, cfg: RaftConfig, draw_depth: Optional[int] = None):
        self.cfg = cfg
        self.t = 0
        # Boot state comes from the SAME init as the kernel (models/state.init_state)
        # so even the boot timer draws are shared. The kernel is groups-minor
        # (models/state.py); the C ABI is groups-major ([G][N]... row-major), so
        # arrays transpose at this boundary.
        from raft_kotlin_tpu.models.state import init_state

        st = init_state(cfg)
        self.arrays: Dict[str, np.ndarray] = {}
        for f in dataclasses.fields(st):
            if f.name == "tick" or getattr(st, f.name) is None:
                continue  # §10 mailbox fields absent unless cfg.uses_mailbox
            a = np.asarray(getattr(st, f.name))
            a = a.T if a.ndim == 2 else a.transpose(2, 0, 1)
            dt = np.uint8 if f.name in _STATE_FIELDS_U8 else np.int32
            self.arrays[f.name] = np.ascontiguousarray(a.astype(dt))
        # Counted-draw tables; grown on exhaustion (ERR_DRAW_EXHAUSTED retry).
        self._Kt = self._Kb = 0
        self._timeout = self._backoff = None
        self._ensure_tables(draw_depth or 256)

    def _ensure_tables(self, K: int) -> None:
        if K <= self._Kt:
            return
        self._Kt = self._Kb = K
        self._timeout = _draw_tables(
            self.cfg, rngmod.KIND_TIMEOUT, K, self.cfg.el_lo, self.cfg.el_hi)
        self._backoff = _draw_tables(
            self.cfg, rngmod.KIND_BACKOFF, K, self.cfg.bo_lo, self.cfg.bo_hi)

    def run(self, n_ticks: int, inject: Optional[np.ndarray] = None,
            fault_cmd: Optional[np.ndarray] = None, trace: bool = True):
        """Advance n_ticks; returns {field: (T, G, N) int32} if trace else None.
        inject: optional (T, G, N) int32 command ids (-1 = none); fault_cmd:
        optional (T, G, N) uint8 (1 = crash, 2 = restart)."""
        cfg = self.cfg
        G, N = cfg.n_groups, cfg.n_nodes
        masks = _tick_masks(cfg, self.t, n_ticks)
        if inject is not None:
            inject = np.ascontiguousarray(inject, dtype=np.int32)
            assert inject.shape == (n_ticks, G, N)
        if fault_cmd is not None:
            fault_cmd = np.ascontiguousarray(fault_cmd, dtype=np.uint8)
            assert fault_cmd.shape == (n_ticks, G, N)

        tr = {k: np.empty((n_ticks, G, N), dtype=np.int32) for k in TRACE_FIELDS} \
            if trace else None

        while True:
            snapshot = {k: a.copy() for k, a in self.arrays.items()}
            dims = _Dims(
                G=G, N=N, C=cfg.log_capacity, hb_ticks=cfg.hb_ticks,
                round_ticks=cfg.round_ticks, retry_ticks=cfg.retry_ticks,
                majority=cfg.majority, cmd_period=cfg.cmd_period,
                cmd_node=cfg.cmd_node, t0=self.t, T=n_ticks,
                Kt=self._Kt, Kb=self._Kb,
                delay_lo=cfg.delay_lo, delay_hi=cfg.delay_hi,
                mailbox=1 if cfg.uses_mailbox else 0,
                compact_watermark=cfg.compact_watermark,
                compact_chunk=cfg.compact_chunk,
                ring_capacity=cfg.ring_capacity or 0,
            )
            state = _State(**{
                k: _ptr(self.arrays.get(k), typ) for k, typ in _STATE_ORDER
            })
            inputs = _Inputs(
                timeout_draws=_ptr(self._timeout, _I32P),
                backoff_draws=_ptr(self._backoff, _I32P),
                edge_ok=_ptr(masks["edge_ok"], _U8P),
                crash_m=_ptr(masks["crash_m"], _U8P),
                restart_m=_ptr(masks["restart_m"], _U8P),
                link_fail=_ptr(masks["link_fail"], _U8P),
                link_heal=_ptr(masks["link_heal"], _U8P),
                inject=_ptr(inject, _I32P),
                fault_cmd=_ptr(fault_cmd, _U8P),
                delay=_ptr(masks["delay"], _I32P),
                leader_iso=_ptr(masks["leader_iso"], _U8P),
            )
            trace_s = _Trace(**({k: _ptr(tr[k], _I32P) for k in TRACE_FIELDS}
                                if trace else {}))
            rc = _lib().raft_run(ct.byref(dims), ct.byref(state), ct.byref(inputs),
                                 ct.byref(trace_s) if trace else None)
            if rc == 0:
                break
            if rc == 1:  # draws exhausted: restore the pre-run state, deepen, retry
                self.arrays = snapshot
                self._ensure_tables(self._Kt * 2)
                continue
            raise RuntimeError(f"raft_run failed with code {rc}")

        self.t += n_ticks
        return tr
