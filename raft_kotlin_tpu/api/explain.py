"""Oracle-replay "explain" mode: a per-event narrative for ONE group.

The reference's single observability asset is its per-exchange log trail —
kLogger.info on every vote/append (reference RaftServer.kt:56,110,134-135,222,
255,280) plus a raw println of per-peer append state (RaftServer.kt:134). The
vectorized kernel deliberately has no per-event path (it computes 100k groups
as array ops), so this module recovers the narrative the cheap way: replay the
requested group on the scalar Python oracle — same counted-threefry seed ⇒
same bits as the kernel (the differential suite proves it) — with the oracle's
event sink on, and render the events as a per-tick, per-phase story: timer
fires, vote exchanges with grant/reject reasons, append outcomes, commit
advances.

    python -m raft_kotlin_tpu explain --groups 64 --nodes 5 --p-drop 0.2 \
        --stress 10 --group 3 --ticks 40..80
"""

from __future__ import annotations

import sys
from typing import List, Optional, TextIO

from raft_kotlin_tpu.models.oracle import (
    OracleGroup,
    make_edge_ok_fn,
    make_faults_fn,
)
from raft_kotlin_tpu.utils.config import RaftConfig


def replay_events(cfg: RaftConfig, group: int, until: int,
                  schedule=None, fault_schedule=None) -> List[dict]:
    """Replay `group` for `until` ticks on the oracle with the event sink on;
    returns the flat event list (each event carries its tick). `schedule` /
    `fault_schedule` mirror OracleGroup.inject/crash/restart pre-loads:
    {tick: [(node, cmd)]} / {tick: [(node, "crash"|"restart")]}."""
    grp = OracleGroup(cfg, group)
    grp.events = []
    if schedule:
        for t, items in schedule.items():
            for node, cmd in items:
                grp.inject(t, node, cmd)
    if fault_schedule:
        for t, items in fault_schedule.items():
            for node, kind in items:
                (grp.crash if kind == "crash" else grp.restart)(t, node)
    grp.run(until, make_edge_ok_fn(cfg, group), make_faults_fn(cfg, group),
            trace=False)
    return grp.events


def _vote_reason(e: dict) -> str:
    """Derive the grant/reject reason from the §6.1 decision table
    (reference RaftServer.kt:228-251) using the peer's pre-state carried on the
    event — presentation only; the decision itself was made by vote_handler."""
    rt, pt = e["req_term"], e["peer_pre_term"]
    if rt < pt:
        return f"stale term {rt} < {pt}"
    if rt == pt:
        if e["granted"]:
            return f"equal term, votedFor already {e['cand']} (quirk g)"
        return f"equal term, votedFor={e['peer_pre_voted_for']} != {e['cand']}"
    lli, llt = e["peer_pre_lli"], e["peer_pre_llt"]
    if e["granted"]:
        return f"higher term, log ok -> peer adopts term {rt}"
    if lli >= 1 and e["req_llt"] < llt:
        return f"higher term but log stale (llt {e['req_llt']} < {llt}; no adopt, quirk f)"
    return (f"higher term but log short (lli {e['req_lli']} < {lli}; "
            "no adopt, quirk f)")


def format_event(e: dict) -> str:
    t, ph, k = e["tick"], e["phase"], e["kind"]
    head = f"[t={t:>5} p{ph}] "
    if k == "crash":
        return head + f"n{e['node']} CRASH ({e['via']})"
    if k == "restart":
        return head + (f"n{e['node']} RESTART ({e['via']}): state wiped "
                       f"(quirk l), timer re-armed ({e['el_left']} ticks)")
    if k == "command":
        got = "accepted" if e["accepted"] else "REJECTED (log full)"
        return head + (f"n{e['node']} local write cmd={e['cmd']} at index "
                       f"{e['at']} term {e['term']} ({e['via']}): {got}")
    if k == "election_timeout":
        return head + (f"n{e['node']} election timer fired -> CANDIDATE "
                       f"(term {e['term']})")
    if k == "backoff_expired":
        return head + f"n{e['node']} backoff expired, new round next"
    if k == "round_start":
        return head + (f"n{e['node']} starts vote round #{e['round']} at term "
                       f"{e['term']} (votedFor=self)")
    if k == "demoted_timer_reset":
        return head + (f"n{e['node']} no longer CANDIDATE; while-loop exits, "
                       f"timer reset ({e['el_left']} ticks)")
    if k == "vote_sent":
        return head + (f"n{e['cand']} -> n{e['peer']} RequestVote(term="
                       f"{e['req_term']}) in flight, due in {e['due']}")
    if k == "vote_dropped":
        return head + (f"n{e['cand']} <- n{e['peer']} vote response LOST "
                       f"(edge down)")
    if k == "vote_straggler":
        return head + (f"n{e['cand']} <- n{e['peer']} vote response arrived "
                       f"after round closed: peer mutated, tally unchanged")
    if k == "vote":
        verdict = "GRANTED" if e["granted"] else "rejected"
        s = head + (f"n{e['cand']} <-> n{e['peer']} Vote(term={e['req_term']}, "
                    f"lli={e['req_lli']}, llt={e['req_llt']}): {verdict} "
                    f"({_vote_reason(e)}); votes={e['cand_votes']}/"
                    f"{e['cand_responses']} responses")
        if e["cand_demoted"]:
            s += f"; candidate demoted by resp term {e['resp_term']} (quirk f)"
        return s
    if k == "won_election":
        return head + (f"n{e['node']} WINS term {e['term']} with {e['votes']}/"
                       f"{e['responses']} votes -> LEADER; nextIndex[*]="
                       f"{e['next_index']} (quirk b), heartbeat armed")
    if k == "lost_round":
        why = "latch timed out" if e["timed_out"] else "majority responded, too few grants"
        return head + (f"n{e['node']} loses round at term {e['term']} "
                       f"({e['votes']}/{e['responses']} votes; {why}); "
                       f"backoff {e['backoff']} ticks")
    if k == "concluded_demoted":
        return head + (f"n{e['node']} round concluded while demoted; timer "
                       f"reset ({e['el_left']} ticks)")
    if k == "heartbeat":
        s = head + f"n{e['leader']} heartbeat fires (term {e['term']})"
        if e["final"]:
            s += " — FINAL round (cancelled as FOLLOWER, RaftServer.kt:117)"
        return s
    if k == "append_sent":
        what = f"entry {e['entry']}" if e["entry"] else "empty (pure heartbeat)"
        return head + (f"n{e['leader']} -> n{e['peer']} Append(pli={e['pli']}, "
                       f"{what}) in flight, due in {e['due']}")
    if k == "append_dropped":
        return head + f"n{e['leader']} x n{e['peer']} append exchange dropped"
    if k == "skip_peer":
        return head + (f"n{e['leader']} skips n{e['peer']}: {e['reason']} "
                       f"(nextIndex={e['next_index']}, quirk i)")
    if k == "leader_demoted":
        return head + (f"n{e['leader']} demoted by append response term "
                       f"{e['resp_term']} from n{e['peer']} -> FOLLOWER")
    if k == "append":
        what = f"entry {e['entry']}" if e["entry"] else "heartbeat"
        s = head + (f"n{e['leader']} -> n{e['peer']} Append(pli={e['pli']}, "
                    f"plt={e['plt']}, {what}): "
                    f"{'success' if e['success'] else 'FAIL'}; "
                    f"nextIndex={e['next_index']}, matchIndex={e['match_index']}")
        pc0, pc1 = e["peer_commit"]
        if pc1 != pc0:
            s += f"; peer commit {pc0}->{pc1} (quirk e)"
        lc0, lc1 = e["leader_commit"]
        if lc1 != lc0:
            s += f"; LEADER COMMIT {lc0}->{lc1} (quirk a)"
        return s
    return head + str({k2: v for k2, v in e.items() if k2 not in ("tick", "phase")})


def explain(cfg: RaftConfig, group: int, tick_lo: int, tick_hi: int,
            out: Optional[TextIO] = None, schedule=None,
            fault_schedule=None) -> List[dict]:
    """Replay and print the [tick_lo, tick_hi] event narrative of one group.
    Returns the events in the window (all phases, oracle order — which IS the
    canonical serialization the kernel implements)."""
    out = out or sys.stdout
    events = replay_events(cfg, group, tick_hi + 1, schedule, fault_schedule)
    window = [e for e in events if tick_lo <= e["tick"] <= tick_hi]
    print(f"# group {group}, ticks {tick_lo}..{tick_hi}: "
          f"{len(window)} events (seed {cfg.seed})", file=out)
    for e in window:
        print(format_event(e), file=out)
    return window


def explain_text(cfg: RaftConfig, group: int, tick_lo: int, tick_hi: int,
                 schedule=None, fault_schedule=None):
    """explain() rendered into a string: (events, text). The form
    api/triage.py attaches to a divergence report (the triage artifact
    carries the narrative, not just a pointer to it)."""
    import io

    buf = io.StringIO()
    events = explain(cfg, group, tick_lo, tick_hi, out=buf,
                     schedule=schedule, fault_schedule=fault_schedule)
    return events, buf.getvalue()
