"""Deterministic simulation-fuzzing farm (ROADMAP item 5 / ISSUE 9).

The repo's throughput is ~40M group-steps/s of FoundationDB-style
deterministic-simulation capacity (PAPERS.md names that harness as the
lineage of the triage design); this module spends it on verification.
Every group of a farm batch is a distinct, reproducible UNIVERSE: its
fault lattice (drop/crash/restart/link probabilities as integer-exact
23-bit thresholds), delay window and scripted partition program are
sampled from a counted threefry stream keyed by
(farm_seed, universe_id) — utils/rng.sample_scenario_bank via
`RaftConfig.scenario` (utils/config.ScenarioSpec), threaded through every
engine's rng operand by ops/tick.make_rng. The on-device monitor
(utils/telemetry, PR 6) checks the Figure-3 invariants per tick, latches
the first violation, and — with `monitor_groups` — accumulates per-
universe stress counters, all in the scan carry: a batch costs ONE device
round trip.

The farm loop (`fuzz_farm` / scripts/fuzz_farm.py):
1. run monitored+recorded batches over the sampled manifest,
2. on a latch, AUTO-SHRINK the violation (`shrink_violation`): tighten
   the tick horizon while the latch persists, then zero the scenario's
   fault channels one at a time keeping only the ones the violation
   needs,
3. write the minimal replayable artifact — (farm_seed, universe params,
   config, tick, group, invariant) — to a JSONL corpus whose bytes are a
   pure function of the farm inputs (`corpus_hash` pins determinism),
4. re-confirm by replay: `replay_artifact` re-runs the shrunk config
   from scratch and requires the latch at the exact coordinate, and
   pure (non-mutated) violations additionally go through
   api/triage.triage_violation for the device-replay + explain()
   narrative.

A correct implementation never latches, so the farm's own acceptance
machinery is exercised through SEEDED MUTATION (`committed_rewrite_mutator`
/ `twin_leader_mutator`): a deliberately broken transition injected
inside the scan at an exact (tick, group), which must latch, shrink to
zero fault channels, and replay at exactly the injected coordinate
(tests/test_fuzz.py).
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib
import json
import os
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from raft_kotlin_tpu.utils import telemetry as telemetry_mod
from raft_kotlin_tpu.utils.config import RaftConfig, ScenarioSpec, config_from_dict

_I32 = jnp.int32

CORPUS_SCHEMA = "raft-fuzz-v1"


def _cpu_batched_guard(cfg: RaftConfig) -> Optional[bool]:
    """The repo-wide CPU guard: XLA:CPU compiles of the batched deep
    engine blow up (ops/tick.py), so deep configs take the per-pair
    engine on CPU — bit-identical, just slower."""
    return False if (cfg.uses_dyn_log
                     and jax.default_backend() == "cpu") else None


def _monitor_shardings(mesh, n_groups: int, n_ticks: int,
                       timing: bool = False, sched: bool = False,
                       series: int = 0, series_stride: int = 0,
                       events: int = 0):
    """NamedShardings for the RAW per-group monitor carry under `mesh`:
    the (G,)-BY-CONTRACT keys (PER_GROUP_KEYS stress counters + the taint
    masks + every §19 grp_* scheduler/timing row) place on the groups axis
    like the state arrays; scalars, the history ring, the latch and the
    (B,) timing histograms replicate (integer sums are order-independent,
    so the psum'd histogram is bit-equal to single-device). Keyed by NAME,
    not by shape — a shape rule would mis-shard the (W,) ring whenever
    n_groups happened to equal the window count. (The rng operand's
    placement stays in mesh.rng_shardings, where shape IS the contract:
    bank channels are (G,) by construction. These were the two
    single-device assumptions the r13 pod work removed.)"""
    from jax.sharding import NamedSharding, PartitionSpec as P

    rep = NamedSharding(mesh, P())
    lanes = NamedSharding(mesh, P(("dcn", "ici")))
    mon0 = jax.eval_shape(
        lambda: telemetry_mod.monitor_init(n_groups, n_ticks,
                                           per_group=True, timing=timing,
                                           sched=sched, series=series,
                                           series_stride=series_stride,
                                           events=events))
    # The §21 series/event rings replicate by the same name rule: none of
    # their keys carries the grp_/taint_ prefix, and their integer sums /
    # extrema / cursor scatters are group-order-independent, so the
    # replicated fold is bit-equal to single-device.
    per_group = {k for k in mon0
                 if k.startswith("grp_") or k.startswith("taint_")}
    for k in per_group:
        assert mon0[k].shape == (n_groups,), k  # the (G,) contract itself
    return {k: (lanes if k in per_group else rep) for k in mon0}


def make_batch_runner(cfg: RaftConfig, n_ticks: int,
                      mutator: Optional[Callable] = None, mesh=None):
    """run(state0?) -> (end_state, telemetry, RAW per-group monitor carry)
    for one monitored+recorded batch — the farm's engine. One jit, one
    scan, per-universe counters in the carry (monitor_groups), monitor
    returned UN-finalized so the (G,) taint masks and PER_GROUP_KEYS are
    readable (telemetry.universe_stats).

    `mutator(state, tick_scalar) -> state` is the seeded-mutation hook:
    applied to the POST-tick state inside the scan, BEFORE the monitor
    step — a deliberately broken transition the monitor must catch.

    `mesh` (ISSUE 10): shard the batch's UNIVERSES over a device mesh —
    the scenario bank rides the rng operand placed by mesh.rng_shardings
    (groups axis), the per-universe stress counters stay (G,)-wide and
    sharded in the carry (_monitor_shardings), and the tick is the same
    embarrassingly parallel program every sharded runner compiles, so
    scenario throughput multiplies with the pod while the bits (and the
    corpus hash) stay EXACTLY the single-device ones
    (tests/test_pod.py)."""
    from raft_kotlin_tpu.models.state import init_state
    from raft_kotlin_tpu.ops.tick import make_rng, make_tick

    if mesh is None:
        tick = make_tick(cfg, batched=_cpu_batched_guard(cfg))
        tick_fn = lambda s, rng: tick(s, rng=rng)
        jit_kw = {}
        rng = make_rng(cfg)
        mk_state = lambda: init_state(cfg)
    else:
        import math as _math

        from raft_kotlin_tpu.parallel import mesh as mesh_mod

        n_dev = _math.prod(mesh.devices.shape)
        assert cfg.n_groups % n_dev == 0, "pad_groups first"
        if cfg.uses_dyn_log:
            smt = mesh_mod._make_shardmap_xla_tick(cfg, mesh)
            tick_fn = lambda s, rng: smt(s, rng)
        else:
            tick = make_tick(cfg)
            tick_fn = lambda s, rng: tick(s, rng=rng)
        sh = mesh_mod.state_sharding(mesh, cfg)
        rng_sh = mesh_mod.rng_shardings(cfg, mesh)
        rep = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec())
        mon_sh = _monitor_shardings(mesh, cfg.n_groups, n_ticks,
                                    **telemetry_mod.ops_kw(cfg))
        jit_kw = {"in_shardings": (sh, rng_sh),
                  "out_shardings": (sh, rep, mon_sh)}
        # Computed straight into placement (init_sharded's pattern).
        rng = jax.jit(lambda: make_rng(cfg), out_shardings=rng_sh)()
        mk_state = lambda: mesh_mod.init_sharded(cfg, mesh)

    if mesh is not None:
        # Taint-mask operands shard like every (G,) channel.
        lanes_sh = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec(("dcn", "ici")))
        jit_kw["in_shardings"] = jit_kw["in_shardings"] + (
            lanes_sh, lanes_sh)

    @functools.partial(jax.jit, **jit_kw)
    def run(st, rng, tr0, tu0):
        def body(carry, _):
            s, tel, mon = carry
            s2 = tick_fn(s, rng)
            if mutator is not None:
                s2 = mutator(s2, s.tick)
            tel = telemetry_mod.telemetry_step(s, s2, tel)
            mon = telemetry_mod.monitor_step(s, s2, mon)
            return (s2, tel, mon), None

        tel0 = telemetry_mod.telemetry_zeros()
        mon0 = telemetry_mod.monitor_init(cfg.n_groups, n_ticks,
                                          per_group=True,
                                          **telemetry_mod.ops_kw(cfg))
        # Seed the sticky quirk-taint masks (soak_run carries them across
        # checkpoint-rotated segments — a mid-run segment boundary must
        # not forget that a group restarted in an earlier segment).
        mon0 = dict(mon0)
        mon0["taint_restart"] = mon0["taint_restart"] | tr0
        mon0["taint_unsafe"] = mon0["taint_unsafe"] | tu0
        (end, tel, mon), _ = jax.lax.scan(body, (st, tel0, mon0), None,
                                          length=n_ticks)
        return end, tel, mon

    def call(state0=None, taints=None):
        st = state0 if state0 is not None else mk_state()
        if taints is None:
            z = jnp.zeros((cfg.n_groups,), bool)
            taints = (z, z)
        return run(st, rng, *taints)

    return call


def run_fuzz_batch(cfg: RaftConfig, n_ticks: int,
                   mutator: Optional[Callable] = None, mesh=None) -> dict:
    """One monitored farm batch -> a host-side result dict:
    - "summary": telemetry.summarize_monitor (inv_status, latch, ring...),
    - "latch": the first-violation coordinate or None,
    - "telemetry": flight-recorder counters,
    - "universe": per-group numpy arrays (grp_elections/grp_fault_events/
      grp_violations + taint masks — the stress-ranking channel),
    - "coverage": scalar coverage figures (universes with any fault
      event / election / taint — the "bank actually bit" evidence).
    `mesh` shards the batch's universes across devices (bit-identical —
    see make_batch_runner)."""
    end, tel, mon = make_batch_runner(cfg, n_ticks, mutator=mutator,
                                      mesh=mesh)()
    summary = telemetry_mod.summarize_monitor(mon)
    uni = telemetry_mod.universe_stats(mon)
    cov = {
        "fault_universes": int(np.sum(uni["grp_fault_events"] > 0)),
        "election_universes": int(np.sum(uni["grp_elections"] > 0)),
        "taint_restart_universes": int(np.sum(uni["taint_restart"])),
        "taint_unsafe_universes": int(np.sum(uni["taint_unsafe"])),
        "violation_universes": int(np.sum(uni["grp_violations"] > 0)),
    }
    return {
        "summary": summary,
        "latch": summary["latch"],
        "telemetry": telemetry_mod.summarize_telemetry(tel),
        "universe": uni,
        "coverage": cov,
    }


# -- the §19 continuous universe scheduler -----------------------------------

def make_continuous_runner(cfg: RaftConfig, segment_ticks: int,
                           mutator: Optional[Callable] = None, mesh=None):
    """run(state, uids, reset, seeds) -> (end_state, telemetry, RAW monitor
    carry) — one SEGMENT of the §19 continuous farm (SEMANTICS.md §19).

    Universe identity is operand-only (r17): the scenario bank rides the
    rng operand keyed by `uids`, so between segments the admission loop
    swaps retired lanes' bank rows (make_rng(cfg, uids=...)) and passes the
    retire mask as `reset` — inside the jit, reset lanes FOLD back to
    init_state(cfg, scen=bank) under a per-leaf where on the groups axis
    while surviving lanes carry their state bits forward untouched. No
    recompile (bank values are runtime operands), static shapes, zero
    drain tail. The global tick scalar resets only when EVERY lane resets
    (all-retire boundaries reproduce a fresh static batch bit-for-bit —
    the §19 equality theorem; partial admissions join the global clock,
    still byte-deterministic and replayable from the admission log).

    The monitor carry runs per_group + timing + sched: the §19 retirement
    predicate latches grp_retire_age in the scan, and the downtime /
    election-latency histograms accumulate on-device (one readback per
    segment). `seeds` re-seeds the cross-segment carry rows (taints +
    telemetry.SCHED_SEED_KEYS), cleared under `reset`; the bank's "life"
    row installs grp_life each segment. `mesh` shards lanes exactly like
    make_batch_runner (bit-identical — tests/test_scheduler.py)."""
    from raft_kotlin_tpu.models.state import init_state
    from raft_kotlin_tpu.ops import serving as serving_mod
    from raft_kotlin_tpu.ops.tick import make_rng, make_tick, split_rng
    from raft_kotlin_tpu.utils import rng as rngmod

    spec = cfg.scenario
    assert spec is not None, "continuous scheduling needs cfg.scenario"
    G = cfg.n_groups
    quiesce = spec.quiesce_ticks
    # §20/§21: serving rides the continuous farm when the config compiles
    # it in — the carry becomes a 5th operand threaded ACROSS segments
    # (histograms/totals are farm-global accumulators), with the per-lane
    # rows (SERVING_LANE_KEYS) folded back to init under the reset mask
    # exactly like the state leaves.
    uses_srv = serving_mod.serving_enabled(cfg)

    if mesh is None:
        tick = make_tick(cfg, batched=_cpu_batched_guard(cfg))
        tick_fn = lambda s, rng: tick(s, rng=rng)
        jit_kw = {}
        place_rng = jax.jit(lambda u: make_rng(cfg, uids=u))
        mk_state = lambda: init_state(cfg)
    else:
        import math as _math

        from raft_kotlin_tpu.parallel import mesh as mesh_mod

        n_dev = _math.prod(mesh.devices.shape)
        assert cfg.n_groups % n_dev == 0, "pad_groups first"
        if cfg.uses_dyn_log:
            smt = mesh_mod._make_shardmap_xla_tick(cfg, mesh)
            tick_fn = lambda s, rng: smt(s, rng)
        else:
            tick = make_tick(cfg)
            tick_fn = lambda s, rng: tick(s, rng=rng)
        sh = mesh_mod.state_sharding(mesh, cfg)
        rng_sh = mesh_mod.rng_shardings(cfg, mesh)
        rep = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec())
        lanes_sh = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec(("dcn", "ici")))
        mon_sh = _monitor_shardings(mesh, cfg.n_groups, segment_ticks,
                                    timing=True, sched=True,
                                    **telemetry_mod.ops_kw(cfg))
        seeds_sh = {k: lanes_sh for k in
                    ("taint_restart", "taint_unsafe")
                    + telemetry_mod.SCHED_SEED_KEYS}
        in_sh = (sh, rng_sh, lanes_sh, seeds_sh)
        out_sh = (sh, rep, mon_sh)
        if uses_srv:
            # Serving-carry placement by NAME (the _monitor_shardings
            # discipline): lane rows shard their trailing (G,) axis;
            # histograms, totals and the latch replicate.
            srv_shapes = jax.eval_shape(
                lambda: serving_mod.serving_zeros(G, cfg.serve_slots))
            srv_sh = {}
            for k, v in srv_shapes.items():
                if k in serving_mod.SERVING_LANE_KEYS:
                    axes = (None,) * (v.ndim - 1) + (("dcn", "ici"),)
                    srv_sh[k] = jax.sharding.NamedSharding(
                        mesh, jax.sharding.PartitionSpec(*axes))
                else:
                    srv_sh[k] = rep
            in_sh = in_sh + (srv_sh,)
            out_sh = out_sh + (srv_sh,)
        jit_kw = {"in_shardings": in_sh, "out_shardings": out_sh}
        place_rng = jax.jit(lambda u: make_rng(cfg, uids=u),
                            out_shardings=rng_sh)
        mk_state = lambda: mesh_mod.init_sharded(cfg, mesh)

    def _run(st, rng, reset, seeds, srv):
        base_k, _tk, _bk, scen = split_rng(rng)
        fresh = init_state(cfg, scen=scen)

        def fold(f, c):
            if f.ndim == 0:
                return c  # the tick scalar — handled below
            r = reset.reshape((1,) * (f.ndim - 1) + (G,))
            return jnp.where(r, f, c)

        st = jax.tree_util.tree_map(fold, fresh, st)
        st = st.replace(tick=jnp.where(jnp.all(reset),
                                       jnp.zeros((), _I32), st.tick))
        if uses_srv:
            srv_kw = rngmod.kt_key_words(base_k)
            fresh_srv = serving_mod.serving_init(cfg)
            srv = {k: (fold(fresh_srv[k], v)
                       if k in serving_mod.SERVING_LANE_KEYS else v)
                   for k, v in srv.items()}

        def body(carry, _):
            s, tel, mon, srv = carry
            s2 = tick_fn(s, rng)
            if mutator is not None:
                s2 = mutator(s2, s.tick)
            tel = telemetry_mod.telemetry_step(s, s2, tel)
            srv_prev = srv
            if uses_srv:
                srv = serving_mod.serving_step(
                    cfg, serving_mod.serving_view(s2), srv, kw=srv_kw,
                    scen=scen)
            mon = telemetry_mod.monitor_step(s, s2, mon,
                                             srv_prev=srv_prev,
                                             srv_cur=srv)
            return (s2, tel, mon, srv), None

        tel0 = telemetry_mod.telemetry_zeros()
        mon0 = dict(telemetry_mod.monitor_init(
            G, segment_ticks, per_group=True, timing=True, sched=True,
            quiesce_ticks=quiesce, **telemetry_mod.ops_kw(cfg)))
        zb = jnp.zeros((G,), bool)
        zi = jnp.zeros((G,), _I32)
        mon0["taint_restart"] = jnp.where(reset, zb, seeds["taint_restart"])
        mon0["taint_unsafe"] = jnp.where(reset, zb, seeds["taint_unsafe"])
        for k in telemetry_mod.SCHED_SEED_KEYS:
            mon0[k] = jnp.where(reset, zi, seeds[k])
        mon0["grp_life"] = scen.get("life", zi)
        (end, tel, mon, srv), _ = jax.lax.scan(
            body, (st, tel0, mon0, srv), None, length=segment_ticks)
        if uses_srv:
            return end, tel, mon, srv
        return end, tel, mon

    # The jit signature is 4-arg or 5-arg by CONFIG, never a None operand
    # threaded through shardings — serving-off farms keep the exact
    # pre-§21 program.
    if uses_srv:
        run = functools.partial(jax.jit, **jit_kw)(_run)
    else:
        run = functools.partial(jax.jit, **jit_kw)(
            lambda st, rng, reset, seeds: _run(st, rng, reset, seeds,
                                               None))

    def zero_seeds():
        zb = jnp.zeros((G,), bool)
        zi = jnp.zeros((G,), _I32)
        return {"taint_restart": zb, "taint_unsafe": zb,
                **{k: zi for k in telemetry_mod.SCHED_SEED_KEYS}}

    def call(state=None, uids=None, reset=None, seeds=None, srv=None):
        st = state if state is not None else mk_state()
        if uids is None:
            uids = spec.universe_base + np.arange(G, dtype=np.int32)
        rng = place_rng(jnp.asarray(uids, _I32))
        if reset is None:
            reset = jnp.ones((G,), bool)
        if seeds is None:
            seeds = zero_seeds()
        if not uses_srv:
            return run(st, rng, jnp.asarray(reset, bool), seeds)
        if srv is None:
            srv = serving_mod.serving_init(cfg)
        return run(st, rng, jnp.asarray(reset, bool), seeds, srv)

    return call


def static_drain_util(cfg: RaftConfig) -> float:
    """Modeled lane utilization of the STATIC-batch baseline at cfg's
    sampled lifetime mix: a static batch must run every lane to the
    longest lifetime in the batch (the drain tail), so
    useful/total = sum(life) / (G * max(life)). Arithmetic over the same
    bank rows the continuous run installs — a model, not a measurement
    (and on this box even the measured side is CPU-hosted: ROUND19.md)."""
    from raft_kotlin_tpu.models.oracle import scenario_bank_np

    spec = cfg.scenario
    assert spec is not None and spec.life_hi > 0, (
        "static_drain_util needs a lifetime channel (scenario.life_hi > 0)")
    life = np.asarray(scenario_bank_np(cfg)["life"], np.float64)
    return float(life.sum() / (life.size * life.max()))


def continuous_corpus_hash(records, admit_log, farm_seed, groups: int,
                           segments: int, segment_ticks: int) -> str:
    """The §19 corpus hash: the canonical violation records PLUS the
    ordered retire/admit log — equal farm inputs => equal retire/admit
    ORDER => equal hash (the admission sequence is part of the corpus
    bytes, as §19 requires)."""
    payload = json.dumps(
        {"schema": CORPUS_SCHEMA + "+cont", "farm_seed": farm_seed,
         "groups": groups, "segments": segments,
         "segment_ticks": segment_ticks, "admits": admit_log,
         "records": corpus_lines(records)},
        sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


def continuous_farm(cfg: RaftConfig, segment_ticks: int, segments: int,
                    out_path: Optional[str] = None, verbose: bool = False,
                    mutator: Optional[Callable] = None, mesh=None,
                    slo=None, publish: Optional[Callable] = None) -> dict:
    """The §19 standing farm: run `segments` segments of `segment_ticks`
    through make_continuous_runner, retiring and re-admitting lanes
    between segments so every lane stays hot (no drain tail). Per segment:
    ONE readback (monitor summary + universe/scheduler/timing stats),
    then host-side admission — each retired lane gets universe_id =
    universe_base + next_serial in lane order, its bank row re-sampled by
    the next segment's rng operand, its state folded to init under the
    reset mask. Deterministic end to end: the retire/admit order itself is
    hashed (continuous_corpus_hash).

    farm_util accounting: a retired lane's ticks AFTER its retirement age
    (it keeps ticking until the segment boundary) are the only waste, so
    farm_util = 1 - sum(age_end - retire_age) / total lane-ticks. The
    static baseline for the same mix is static_drain_util's drain-tail
    model.

    Violations: the latching lane retires via the predicate's violation
    arm and is re-admitted like any other; the latch coordinate is
    recorded as a continuous-mode artifact (segment + segment-relative
    tick + universe_id — no auto-shrink: shrink_violation assumes static
    batches; replay = rerun the farm, which is deterministic).

    §21 ops plane: `slo` (an opsplane.SLOSpec) gates the per-segment
    metrics — downtime_frac / election_p90 from the fresh-per-segment
    monitor carry, read_p99 from the serving histogram DELTA (the serving
    carry threads across segments, so per-segment = cur - prev host
    copies), farm_util from the retire-age waste — through error-budget
    burn (opsplane.SLOBurn); result grows slo_status/slo_burn. `publish`
    (e.g. opsplane.OpsPlane.update) receives one host snapshot dict per
    segment, built from the SAME readback set the loop already
    materializes — zero extra device syncs for the scrape surface."""
    from raft_kotlin_tpu.api import opsplane as opsplane_mod
    from raft_kotlin_tpu.ops import serving as serving_mod

    spec = cfg.scenario
    assert spec is not None, "continuous_farm needs cfg.scenario"
    G = cfg.n_groups
    uses_srv = serving_mod.serving_enabled(cfg)
    runner = make_continuous_runner(cfg, segment_ticks, mutator=mutator,
                                    mesh=mesh)
    uids = spec.universe_base + np.arange(G, dtype=np.int64)
    next_serial = G
    state, seeds, srv = None, None, None
    burn = opsplane_mod.SLOBurn(slo) if slo is not None else None
    prev_hist_read = np.zeros(serving_mod.SERVING_BINS, np.int64)
    events_dropped_total = 0
    last_series, last_events = None, None
    reset = np.ones((G,), bool)
    admit_log: list = []
    records: list = []
    statuses: list = []
    status = "clean"
    retired_total, wasted = 0, 0
    tel_total: dict = {}
    cov_total = {"fault_universes": 0, "election_universes": 0,
                 "taint_restart_universes": 0, "taint_unsafe_universes": 0,
                 "violation_universes": 0}
    bins = telemetry_mod.TIMING_BINS
    hist_down = np.zeros(bins, np.int64)
    hist_elect = np.zeros(bins, np.int64)
    down_ticks = 0
    for seg in range(segments):
        out = runner(state=state, uids=uids, reset=reset, seeds=seeds,
                     srv=srv)
        if uses_srv:
            state, tel, mon, srv = out
        else:
            state, tel, mon = out
        summ = telemetry_mod.summarize_monitor(mon)
        uni = telemetry_mod.universe_stats(mon)
        sch = telemetry_mod.sched_stats(mon)
        statuses.append(summ["inv_status"])
        for k, v in telemetry_mod.summarize_telemetry(tel).items():
            tel_total[k] = tel_total.get(k, 0) + v
        cov_total["fault_universes"] += int(
            np.sum(uni["grp_fault_events"] > 0))
        cov_total["election_universes"] += int(
            np.sum(uni["grp_elections"] > 0))
        cov_total["taint_restart_universes"] += int(
            np.sum(uni["taint_restart"]))
        cov_total["taint_unsafe_universes"] += int(
            np.sum(uni["taint_unsafe"]))
        cov_total["violation_universes"] += int(
            np.sum(uni["grp_violations"] > 0))
        hist_down += sch["hist_downtime"].astype(np.int64)
        hist_elect += sch["hist_elect"].astype(np.int64)
        down_ticks += int(sch["down_ticks"])
        retire_age = sch["grp_retire_age"]
        age_end = sch["grp_age"]
        retired = retire_age >= 0
        wasted_seg = int(np.sum(np.where(retired, age_end - retire_age, 0)))
        wasted += wasted_seg
        if summ["latch"] is not None:
            g = int(summ["latch"]["group"])
            art = {
                "schema": CORPUS_SCHEMA + "+cont",
                "farm_seed": spec.farm_seed,
                "universe_id": int(uids[g]),
                "universe": _continuous_universe_params(cfg, int(uids[g])),
                "segment": seg,
                "tick": int(summ["latch"]["tick"]),
                "group": g,
                "invariant": summ["latch"]["invariant"],
                "invariant_id": int(summ["latch"]["invariant_id"]),
                "status": summ["inv_status"],
                "mutated": mutator is not None,
            }
            records.append(art)
            if status == "clean":
                status = summ["inv_status"]
            if verbose:
                print(f"LATCH: {summ['inv_status']} in segment {seg} "
                      f"(universe {int(uids[g])})")
        lanes = np.nonzero(retired)[0]
        for lane in lanes:
            new_uid = spec.universe_base + next_serial
            admit_log.append([seg, int(lane), int(uids[lane]),
                              int(new_uid)])
            uids[lane] = new_uid
            next_serial += 1
        retired_total += len(lanes)
        reset = retired.copy()
        seeds = {k: mon[k] for k in ("taint_restart", "taint_unsafe")
                 + telemetry_mod.SCHED_SEED_KEYS}
        # §21 per-segment metrics for the SLO gate and the scrape
        # snapshot — every value below is a host read of arrays the loop
        # already pulled (summ/sch/uni), or of the serving carry that
        # call() returns anyway. The monitor carry is rebuilt fresh each
        # segment, so sch values are per-segment directly; the serving
        # histograms thread ACROSS segments, so per-segment = delta
        # against the previous host copy.
        seg_lane_ticks = G * segment_ticks
        metrics = {
            "downtime_frac": int(sch["down_ticks"]) / seg_lane_ticks,
            "election_p90": serving_mod.hist_percentile(
                sch["hist_elect"], 0.90),
            "farm_util": 1.0 - wasted_seg / seg_lane_ticks,
            "read_p99": None,
        }
        if uses_srv:
            cur_hist = np.asarray(jax.device_get(srv["hist_read"]),
                                  np.int64)
            delta = cur_hist - prev_hist_read
            # A segment with zero completed reads has NO latency sample —
            # report None (SLOSpec: absent metric cannot violate), not a
            # fake p99 of 0.
            metrics["read_p99"] = (
                serving_mod.hist_percentile(delta, 0.99)
                if int(delta.sum()) > 0 else None)
            prev_hist_read = cur_hist
        if burn is not None:
            burn.observe(metrics)
        events_dropped_total += int(summ.get("events_dropped", 0))
        last_series = summ.get("series", last_series)
        seg_events = list(summ.get("events") or [])
        # Host-side admission is part of the segment's story: append the
        # admit rows as synthetic events (kind_id -1 — not a device ring
        # kind) so /events and render_events show the full narrative.
        for row in admit_log[len(admit_log) - len(lanes):]:
            seg_events.append({"kind": "admit", "kind_id": -1,
                               "tick": segment_ticks - 1,
                               "group": row[1], "arg": row[3]})
        last_events = seg_events if seg_events else last_events
        if publish is not None:
            publish({
                "segment": seg,
                "ticks_total": (seg + 1) * seg_lane_ticks,
                "universes_admitted": G + retired_total,
                "universes_retired": retired_total,
                "events_dropped": events_dropped_total,
                "farm_util": metrics["farm_util"],
                "downtime_frac": metrics["downtime_frac"],
                "election_p90": metrics["election_p90"],
                "read_p99": metrics["read_p99"],
                "inv_status": status,
                "slo_status": burn.status if burn is not None else "clean",
                "slo_burn": burn.burn if burn is not None else 0.0,
                "telemetry": dict(tel_total),
                "series": summ.get("series"),
                "events": seg_events,
            })
        if verbose:
            print(f"segment {seg}: inv={summ['inv_status']} "
                  f"retired={len(lanes)} serial={next_serial}")
    total = G * segment_ticks * segments
    useful = total - wasted
    result = {
        "schema": CORPUS_SCHEMA + "+cont",
        "farm_seed": spec.farm_seed,
        "groups": G,
        "segments": segments,
        "segment_ticks": segment_ticks,
        "universe_ticks": total,
        "useful_ticks": useful,
        "wasted_ticks": wasted,
        "farm_util": useful / total if total else 0.0,
        "universes_admitted": G + retired_total,
        "universes_retired": retired_total,
        "inv_status": status,
        "statuses": statuses,
        "violations": len(records),
        "records": records,
        "admit_log": admit_log,
        "coverage": cov_total,
        "telemetry": tel_total,
        "hist_downtime": hist_down.tolist(),
        "hist_elect": hist_elect.tolist(),
        "down_ticks": down_ticks,
        "slo_status": burn.status if burn is not None else "clean",
        "slo_burn": burn.as_dict() if burn is not None else None,
        "serving": (serving_mod.summarize_serving(srv)
                    if uses_srv else None),
        "events_dropped": events_dropped_total,
        "series": last_series,
        "events": last_events,
        "corpus_hash": continuous_corpus_hash(
            records, admit_log, spec.farm_seed, G, segments, segment_ticks),
    }
    if out_path is not None:
        with open(out_path, "w") as f:
            for line in corpus_lines(records):
                f.write(line + "\n")
    return result


def _continuous_universe_params(cfg: RaftConfig, uid: int) -> dict:
    """The host-readable bank row of ONE universe id (the continuous
    artifact's `universe` field): sample a 1-group bank at
    universe_base = uid — identical values to the lane's rows, because
    draws are keyed by (farm_seed, kind, universe_id) only."""
    spec = cfg.scenario
    if spec is None:
        return {}
    c1 = dataclasses.replace(
        cfg, n_groups=1,
        scenario=dataclasses.replace(spec, universe_base=uid))
    from raft_kotlin_tpu.models.oracle import scenario_bank_np

    return {k: int(v[0]) for k, v in scenario_bank_np(c1).items()}


def churn_life_spec(farm_seed: int = 31, life_lo: int = 40,
                    life_hi: int = 400,
                    quiesce_ticks: int = 0) -> ScenarioSpec:
    """§19 heterogeneous-lifetime universe family: the smoke fault mix
    plus per-group lifetimes and randomized election-timeout windows —
    the continuous scheduler's headline mix (bench's farm_util leg) and
    the §9.3 observatory's spread channel."""
    return ScenarioSpec(
        farm_seed=farm_seed, drop_max=0.25, crash_max=0.02,
        restart_max=0.2, timeout_windows=True,
        life_lo=life_lo, life_hi=life_hi, quiesce_ticks=quiesce_ticks)


def continuous_config(groups: int, farm_seed: int = 31, seed: int = 9,
                      life_lo: int = 40, life_hi: int = 400,
                      quiesce_ticks: int = 0) -> RaftConfig:
    """The §19 continuous-farm batch config over churn_life_spec."""
    return RaftConfig(n_groups=groups, n_nodes=3, log_capacity=32,
                      cmd_period=5, seed=seed,
                      scenario=churn_life_spec(
                          farm_seed, life_lo=life_lo, life_hi=life_hi,
                          quiesce_ticks=quiesce_ticks)).stressed(10)


# -- auto-shrinking ----------------------------------------------------------

def scenario_channels(cfg: RaftConfig):
    """The fault channels a shrink pass can zero, in deterministic order:
    [(name, zeroed config)] — spec channels first, then any scalar
    baselines the config carries."""
    out = []
    spec = cfg.scenario

    def with_spec(**kw):
        return dataclasses.replace(
            cfg, scenario=dataclasses.replace(spec, **kw))

    if spec is not None and not spec.degenerate:
        for ch in ("drop", "crash", "restart", "link_fail", "link_heal"):
            if getattr(spec, f"{ch}_max") > 0:
                out.append((f"scenario.{ch}", with_spec(**{f"{ch}_max": 0.0})))
        if spec.partitions:
            out.append(("scenario.partitions", with_spec(partitions=())))
        if spec.delay_windows:
            out.append(("scenario.delay_windows",
                        with_spec(delay_windows=False)))
    for ch in ("p_drop", "p_crash", "p_restart", "p_link_fail",
               "p_link_heal"):
        if getattr(cfg, ch) > 0:
            out.append((ch, dataclasses.replace(cfg, **{ch: 0.0})))
    return out


def shrink_violation(cfg: RaftConfig, n_ticks: int, latch: dict,
                     mutator_factory: Optional[Callable] = None) -> dict:
    """Auto-shrink a latched violation to its minimal reproducer:
    (1) HALVE the tick horizon while the latch persists (converging on
    latch_tick + 1 — deterministic replays re-latch at the same tick as
    long as the horizon covers it), then (2) zero fault channels one at a
    time, keeping a channel zeroed whenever the latch persists without it
    (the latch may MOVE — the shrunk coordinate is the shrunk config's
    own first violation, re-verified by replay either way).

    `mutator_factory(cfg) -> mutator` rebuilds the seeded mutation for
    each candidate config (None for pure violations). Returns
    {"config", "horizon", "latch", "steps"} — `steps` is the audit trail
    [(kind, detail, kept_shrunk?)]."""
    steps = []

    def latch_of(c, h):
        mut = mutator_factory(c) if mutator_factory is not None else None
        return run_fuzz_batch(c, h, mutator=mut)["latch"]

    horizon = n_ticks
    # Phase 1: horizon halving (floor: the latch tick + 1).
    while horizon > latch["tick"] + 1:
        cand = max(latch["tick"] + 1, horizon // 2)
        if cand == horizon:
            break
        got = latch_of(cfg, cand)
        if got is not None:
            horizon, latch = cand, got
            steps.append(["horizon", cand, True])
        else:
            steps.append(["horizon", cand, False])
            break
    # Phase 2: channel zeroing, one at a time (re-enumerated after each
    # kept shrink — zeroing one channel never changes another's bits, but
    # the candidate list must reflect the current config).
    changed = True
    while changed:
        changed = False
        for name, cand_cfg in scenario_channels(cfg):
            got = latch_of(cand_cfg, horizon)
            if got is not None:
                cfg, latch = cand_cfg, got
                steps.append(["channel", name, True])
                changed = True
                break
            steps.append(["channel", name, False])
        # A kept shrink may have moved the latch earlier — re-tighten.
        while horizon > latch["tick"] + 1:
            cand = max(latch["tick"] + 1, horizon // 2)
            if cand == horizon:
                break
            got = latch_of(cfg, cand)
            if got is None:
                break
            horizon, latch = cand, got
    return {"config": cfg, "horizon": horizon, "latch": latch,
            "steps": steps}


# -- corpus ------------------------------------------------------------------

def universe_params(cfg: RaftConfig, group: int) -> dict:
    """The host-readable bank row of one universe (the artifact's
    `universe` field): {channel: int} for every sampled channel."""
    if cfg.scenario is None:
        return {}
    from raft_kotlin_tpu.models.oracle import scenario_bank_np

    bank = scenario_bank_np(cfg)
    return {k: int(v[group]) for k, v in bank.items()}


def violation_artifact(shrunk: dict, orig_cfg: RaftConfig,
                       mutated: bool = False) -> dict:
    """The minimal replayable corpus record for one shrunk violation."""
    cfg, latch = shrunk["config"], shrunk["latch"]
    spec = cfg.scenario
    g = latch["group"]
    return {
        "schema": CORPUS_SCHEMA,
        "farm_seed": spec.farm_seed if spec is not None else None,
        "universe_id": (spec.universe_base + g) if spec is not None else g,
        # The universe AS SAMPLED (the original batch config's bank row) —
        # the shrunk config may have zeroed channels away entirely.
        "universe": universe_params(orig_cfg, g),
        "config": dataclasses.asdict(cfg),
        "horizon": shrunk["horizon"],
        "tick": latch["tick"],
        "group": g,
        "invariant": latch["invariant"],
        "invariant_id": latch["invariant_id"],
        "status": f"{latch['invariant']}@t{latch['tick']}/g{g}",
        "shrink": shrunk["steps"],
        "mutated": bool(mutated),
        "orig_config": dataclasses.asdict(orig_cfg),
    }


def replay_artifact(artifact: dict,
                    mutator_factory: Optional[Callable] = None) -> bool:
    """Re-confirm a corpus record from scratch: rebuild the config, run
    `horizon` monitored ticks, and require the latch at EXACTLY the
    recorded (tick, group, invariant)."""
    cfg = config_from_dict(artifact["config"])
    mut = mutator_factory(cfg) if mutator_factory is not None else None
    latch = run_fuzz_batch(cfg, artifact["horizon"], mutator=mut)["latch"]
    return (latch is not None
            and latch["tick"] == artifact["tick"]
            and latch["group"] == artifact["group"]
            and latch["invariant_id"] == artifact["invariant_id"])


def corpus_lines(records) -> list:
    """The corpus's canonical JSONL lines (sort_keys, no whitespace
    variance) — byte-determinism is the contract corpus_hash pins."""
    return [json.dumps(r, sort_keys=True, separators=(",", ":"))
            for r in records]


def corpus_hash(records, farm_seed, universes: int, n_ticks: int) -> str:
    """A short content hash over the canonical corpus + farm shape: equal
    inputs => equal corpus bytes => equal hash (tests/test_fuzz.py pins
    this; bench publishes it as fuzz_corpus_hash)."""
    payload = json.dumps(
        {"schema": CORPUS_SCHEMA, "farm_seed": farm_seed,
         "universes": universes, "ticks": n_ticks,
         "records": corpus_lines(records)},
        sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


# -- the farm ----------------------------------------------------------------

def fuzz_farm(cfg: RaftConfig, n_ticks: int, universes: Optional[int] = None,
              batch_groups: Optional[int] = None,
              out_path: Optional[str] = None,
              mutator_factory: Optional[Callable] = None,
              triage_confirm: bool = True, verbose: bool = False,
              mesh=None) -> dict:
    """Run the farm over `universes` universes (default: one batch of
    cfg.n_groups) in batches of `batch_groups`, collecting latches,
    shrinking each to a minimal artifact, replay-confirming, and writing
    the JSONL corpus to `out_path`. Returns the summary dict (the bench
    fuzz leg's record fields live here):

    {"farm_seed", "universes", "ticks_per_universe", "universe_ticks",
     "inv_status", "violations", "coverage", "corpus_hash", "records",
     "telemetry"}.

    Each batch latches at most its lexicographically FIRST violation (the
    monitor's latch is scalar); the farm harvests one artifact per
    violating batch per pass — a real campaign reruns with the offending
    universe's channel zeroed or a different farm_seed to dig further.

    `mesh` (ISSUE 10) shards each batch's universes across the device
    mesh — scenario throughput multiplies with the pod; bits, latches and
    the corpus hash are EXACTLY the single-device ones (the bank is keyed
    by universe_id, never by batch shape or placement; pinned by
    tests/test_pod.py). Shrink and replay confirmation stay single-device
    (shrunk reproducers are tiny). Batch sizes must tile the mesh.
    """
    spec = cfg.scenario
    assert spec is not None, "fuzz_farm needs cfg.scenario (the bank spec)"
    universes = universes if universes is not None else cfg.n_groups
    batch_groups = batch_groups if batch_groups is not None else cfg.n_groups
    if mesh is not None:
        import math as _math

        n_dev = _math.prod(mesh.devices.shape)
        assert batch_groups % n_dev == 0 and universes % batch_groups == 0, (
            "sharded farm batches must tile the mesh: need batch_groups % "
            f"n_devices == 0 and universes % batch_groups == 0, got "
            f"{universes}/{batch_groups}/{n_dev}")
    records = []
    status = "clean"
    tel_total: dict = {}
    cov_total = {"fault_universes": 0, "election_universes": 0,
                 "taint_restart_universes": 0, "taint_unsafe_universes": 0,
                 "violation_universes": 0}
    done = 0
    while done < universes:
        gb = min(batch_groups, universes - done)
        cfg_b = dataclasses.replace(
            cfg, n_groups=gb,
            scenario=dataclasses.replace(
                spec, universe_base=spec.universe_base + done))
        mut = mutator_factory(cfg_b) if mutator_factory is not None else None
        res = run_fuzz_batch(cfg_b, n_ticks, mutator=mut,
                             mesh=mesh if gb == batch_groups else None)
        for k, v in res["telemetry"].items():
            tel_total[k] = tel_total.get(k, 0) + v
        for k in cov_total:
            cov_total[k] += res["coverage"][k]
        if res["latch"] is not None:
            if verbose:
                print(f"LATCH: {res['summary']['inv_status']} in batch at "
                      f"universe_base={spec.universe_base + done}")
            shrunk = shrink_violation(cfg_b, n_ticks, res["latch"],
                                      mutator_factory=mutator_factory)
            art = violation_artifact(shrunk, cfg_b,
                                     mutated=mutator_factory is not None)
            art["replay_confirmed"] = replay_artifact(
                art, mutator_factory=mutator_factory)
            if triage_confirm and mutator_factory is None:
                # Pure violations get the full triage treatment: device
                # replay through ops/tick.make_run + explain() narrative.
                from raft_kotlin_tpu.api.triage import triage_violation

                rec = triage_violation(shrunk["config"], shrunk["latch"],
                                       replay=True)
                art["triage_confirmed"] = bool(rec.get("confirmed"))
            records.append(art)
            if status == "clean":
                status = art["status"]
        done += gb
    result = {
        "schema": CORPUS_SCHEMA,
        "farm_seed": spec.farm_seed,
        "universes": universes,
        "ticks_per_universe": n_ticks,
        "universe_ticks": universes * n_ticks,
        "inv_status": status,
        "violations": len(records),
        "coverage": cov_total,
        "telemetry": tel_total,
        "corpus_hash": corpus_hash(records, spec.farm_seed, universes,
                                   n_ticks),
        "records": records,
    }
    if out_path is not None:
        with open(out_path, "w") as f:
            for line in corpus_lines(records):
                f.write(line + "\n")
    return result


def laggard_spec(farm_seed: int = 21) -> ScenarioSpec:
    """§15 laggard-catch-up universe family: crash/restart-heavy fault
    lattices, so leaders routinely snapshot PAST a crashed follower's
    frontier and the rejoin must travel InstallSnapshot — exactly the
    scenario Raft §7 exists for. Run with a compaction config
    (laggard_config)."""
    return ScenarioSpec(
        farm_seed=farm_seed, drop_max=0.1, crash_max=0.05, restart_max=0.3)


def laggard_config(groups: int, farm_seed: int = 21,
                   seed: int = 9) -> RaftConfig:
    """The §15 laggard-catch-up batch config: a small bounded log window
    with an aggressive watermark, so any committed progress folds quickly
    and crashed-then-restarted followers come back BELOW the leaders'
    snapshot bases."""
    return RaftConfig(n_groups=groups, n_nodes=3, log_capacity=32,
                      cmd_period=5, seed=seed,
                      compact_watermark=4, compact_chunk=4,
                      scenario=laggard_spec(farm_seed)).stressed(10)


def partition_snapshot_spec(farm_seed: int = 22) -> ScenarioSpec:
    """§15 snapshot-during-partition universe family: scripted
    split/asym/leader partition programs over a compacting cluster — the
    isolated side's frontier freezes while the majority side folds, so
    heals exercise the install path under every partition geometry."""
    return ScenarioSpec(
        farm_seed=farm_seed, drop_max=0.15, crash_max=0.01,
        restart_max=0.15, partitions=("split", "asym", "leader"),
        part_period_lo=5, part_period_hi=40)


def partition_snapshot_config(groups: int, farm_seed: int = 22,
                              seed: int = 9) -> RaftConfig:
    """The §15 snapshot-during-partition batch config (see the spec)."""
    return RaftConfig(n_groups=groups, n_nodes=3, log_capacity=32,
                      cmd_period=5, seed=seed,
                      compact_watermark=4, compact_chunk=4,
                      scenario=partition_snapshot_spec(farm_seed)
                      ).stressed(10)


def soak_run(cfg: RaftConfig, n_ticks: int, segment: Optional[int] = None,
             ckpt_dir: Optional[str] = None, verbose: bool = False,
             mesh=None) -> dict:
    """§15 standing-soak service: run `n_ticks` monitored ticks in
    checkpoint-rotated segments — the mode compaction unlocks (without
    truncation every run died at log_capacity; with it a farm universe
    runs forever under rotation). Each segment runs a monitored batch
    from the carried state, checkpoints it, RELOADS the checkpoint and
    continues from the loaded state — so the published end state has
    round-tripped the rotation path, not just the device.

    Returns {"ticks", "segments", "inv_status", "statuses",
    "snap_index_min/max", "window_hw", "cap_exhausted_groups",
    "log_bytes", "telemetry"}: `window_hw` is the live-window high-water
    max(phys_len - snap_index) of the END state — a soak is healthy when
    it stays <= log_capacity with the monitor clean and the latch empty
    (the acceptance shape of ISSUE 12: flat log memory, unbounded
    lifetime). `inv_status` is the first non-clean segment verdict, else
    "clean"."""
    import tempfile

    from raft_kotlin_tpu.models.state import init_state
    from raft_kotlin_tpu.utils import checkpoint as ckpt_mod

    assert cfg.uses_compaction, (
        "soak_run needs a §15 compaction config (compact_watermark > 0) — "
        "without truncation the run dies at log_capacity")
    segment = segment or max(1, min(n_ticks, 2 * cfg.log_capacity))
    tmp = None
    if ckpt_dir is None:
        tmp = tempfile.TemporaryDirectory(prefix="raft_soak_")
        ckpt_dir = tmp.name
    path = os.path.join(ckpt_dir, "soak.npz")
    state = init_state(cfg)
    taints = None
    statuses, tel_total = [], {}
    done, seg_i = 0, 0
    status = "clean"
    try:
        while done < n_ticks:
            t_seg = min(segment, n_ticks - done)
            # Key on the Mesh itself (hashable) — id() of a dead mesh can
            # be recycled and hand back a runner closed over stale devices.
            rkey = (cfg, t_seg, mesh)
            runner = _SOAK_RUNNERS.get(rkey)
            if runner is None:
                runner = make_batch_runner(cfg, t_seg, mesh=mesh)
                _SOAK_RUNNERS[rkey] = runner
                while len(_SOAK_RUNNERS) > _SOAK_RUNNERS_CAP:
                    _SOAK_RUNNERS.pop(next(iter(_SOAK_RUNNERS)))
            else:
                _SOAK_RUNNERS[rkey] = _SOAK_RUNNERS.pop(rkey)  # LRU touch
            state, tel, mon = runner(state, taints=taints)
            taints = (mon["taint_restart"], mon["taint_unsafe"])
            summ = telemetry_mod.summarize_monitor(mon)
            statuses.append(summ["inv_status"])
            if summ["inv_status"] != "clean" and status == "clean":
                status = summ["inv_status"]
            for k, v in telemetry_mod.summarize_telemetry(tel).items():
                tel_total[k] = tel_total.get(k, 0) + v
            # Checkpoint rotation: publish, reload, continue from the
            # loaded state (the resume path IS the soaked path).
            ckpt_mod.save(path, state, cfg,
                          extra={"soak_segment": seg_i, "ticks": done + t_seg})
            state, _ = ckpt_mod.load(path, expect_cfg=cfg)
            done += t_seg
            seg_i += 1
            if verbose:
                si = np.asarray(jax.device_get(state.snap_index))
                print(f"soak segment {seg_i}: ticks {done}/{n_ticks} "
                      f"inv={summ['inv_status']} snap_index "
                      f"[{si.min()}, {si.max()}]")
    finally:
        if tmp is not None:
            tmp.cleanup()
    host = jax.device_get({
        "si": state.snap_index, "pl": state.phys_len, "cap": state.cap_ov,
        "lt": state.log_term})
    si = np.asarray(host["si"])
    window = np.asarray(host["pl"]).astype(np.int64) - si.astype(np.int64)
    return {
        "ticks": done,
        "segments": seg_i,
        "inv_status": status,
        "statuses": statuses,
        "snap_index_min": int(si.min()),
        "snap_index_max": int(si.max()),
        "window_hw": int(window.max()),
        "cap_exhausted_groups": int(
            np.sum(np.any(np.asarray(host["cap"]) != 0, axis=0))),
        "log_bytes": int(np.asarray(host["lt"]).nbytes * 2),
        "telemetry": tel_total,
    }


# Compiled-runner cache for soak segments (same cfg + segment shape reuse
# one jit across rotations — the whole point of the fixed segment size).
# LRU-bounded: a standing service soaking many configs must not pin every
# compiled executable (and its closure's mesh + rng operands) forever.
_SOAK_RUNNERS: dict = {}
_SOAK_RUNNERS_CAP = 8


def smoke_spec(farm_seed: int = 12) -> ScenarioSpec:
    """THE smoke-universe spec: mixed fault lattices + all three partition
    program kinds — one copy shared by bench.py's gated fuzz leg and
    scripts/probe_invariants.py's ranking probe, so the probe always ranks
    the same universe family the bench gates on."""
    return ScenarioSpec(
        farm_seed=farm_seed, drop_max=0.25, crash_max=0.02, restart_max=0.2,
        partitions=("split", "asym", "leader"),
        part_period_lo=5, part_period_hi=40)


def smoke_config(groups: int, farm_seed: int = 12,
                 seed: int = 9) -> RaftConfig:
    """The smoke-batch config over smoke_spec (see there)."""
    return RaftConfig(n_groups=groups, n_nodes=3, log_capacity=32,
                      cmd_period=5, seed=seed,
                      scenario=smoke_spec(farm_seed)).stressed(10)


# -- seeded mutations (the farm's own acceptance harness) --------------------

def committed_rewrite_mutator(cfg: RaftConfig, tick: int, group: int,
                              delta: int = 7777):
    """A deliberately broken transition: at tick `tick`, rewrite the
    stored content of node 1's log slot 0 in `group` — where slot 0 is
    committed and the logs are pristine this is a Figure-8-style
    committed rewrite, latched at exactly (tick, group) with the
    lexicographically FIRST applicable invariant (leader_append_only when
    node 1 is a continuing live leader, log_matching otherwise;
    committed_prefix counts either way). Applied post-tick inside the
    scan (make_batch_runner)."""
    def mutate(state, t):
        hit = (t == tick)
        G = state.log_cmd.shape[-1]
        C = state.log_cmd.shape[1]
        g_hot = jnp.arange(G, dtype=_I32) == group
        slot_hot = (jnp.arange(C, dtype=_I32) == 0)[None, :, None]
        node_hot = (jnp.arange(state.log_cmd.shape[0], dtype=_I32)
                    == 0)[:, None, None]
        m = hit & (node_hot & slot_hot & g_hot[None, None, :])
        lc = jnp.where(m, state.log_cmd + jnp.asarray(
            delta, state.log_cmd.dtype), state.log_cmd)
        return state.replace(log_cmd=lc)

    return mutate


def twin_leader_mutator(cfg: RaftConfig, tick: int, group: int):
    """A deliberately broken transition: at tick `tick`, force nodes 1
    AND 2 of `group` into LEADER at node 1's term — two live same-term
    leaders, an election-safety violation (id 0) regardless of who the
    group's natural leader was."""
    from raft_kotlin_tpu.constants import LEADER

    def mutate(state, t):
        hit = (t == tick)
        G = state.role.shape[-1]
        g_hot = (jnp.arange(G, dtype=_I32) == group)[None, :]
        n12 = (jnp.arange(state.role.shape[0], dtype=_I32) < 2)[:, None]
        m = hit & (n12 & g_hot)
        role = jnp.where(m, jnp.asarray(LEADER, state.role.dtype),
                         state.role)
        term = jnp.where(m, state.term[0][None], state.term)
        up = state.up | m
        return state.replace(role=role, term=term, up=up)

    return mutate
