"""HTTP frontend: the reference's per-node command API, generalized to many groups.

The reference runs an embedded ktor server per node on port 7000+id with exactly two
routes — `GET /` ("Server $id log ${entries()}") and `GET /cmd/{command}` (append the
command to the LOCAL log, no leader check) — see reference RaftServer.kt:81-94 (and
the dead Javalin twin at :72-79). Here one stdlib HTTP server fronts the whole
simulation; routes are addressed by (group, node):

    GET /                                  -> simulation status (tick, groups, leaders)
    GET /{g}/{n}/                          -> "Server n log [...]" (reference GET /)
    GET /{g}/{n}/cmd/{command}             -> append on (g, n), reply with the log dump
                                              (reference GET /cmd/, RaftServer.kt:87-90:
                                              synchronous append + dump; the append
                                              lands in phase 0 of the next tick, which
                                              this route runs/awaits before dumping);
                                              ?async=1 -> queue + ack without waiting
    GET /{g}/{n}/status                    -> up/role/term/commit/lastIndex JSON
    GET /{g}/{n}/crash, /{g}/{n}/restart   -> queue a §9 fault event on (g, n)
    GET /step/{k}                          -> advance k ticks (manual-clock mode)

Serving configs (cfg.serve_slots > 0, SEMANTICS.md §20) add the applied-KV
verbs — GETs routed onto the applied state machine rather than the raw log:

    GET /{g}/kv                            -> whole applied store of group g
    GET /{g}/kv/{slot}                     -> raw (stale-ok) applied read
    GET /{g}/read/{slot}                   -> log-free linearizable read; 503
                                              when no confirmed leader under
                                              cfg.read_path (retry next tick)
    GET /serving                           -> §20 stats: invariant status,
                                              totals, latency percentiles

On serve_slots=0 configs these routes return 400 (serving path disabled).

The §21 ops plane adds the scrape surface (SEMANTICS.md §21):

    GET /metrics                           -> Prometheus text exposition; from
                                              the `ops` snapshot holder when one
                                              is attached (farm mode), else from
                                              sim.metrics_snapshot()
    GET /events                            -> the last published segment's
                                              decoded event-ring JSON (farm mode)
    GET /healthz                           -> 200 ok / 503 on a latched
                                              invariant or breached SLO

`RaftHTTPServer(sim, ..., ops=OpsPlane())` attaches a farm's snapshot
holder; `sim=None` runs the server in FARM MODE — only the three scrape
routes respond (the farm owns the device; there is no simulator to
address). Scrapes never touch the device either way: farm mode reads the
snapshot continuous_farm already published, sim mode reads host-side
state/serving copies under the simulator lock.

With tick_hz > 0 a daemon thread advances the simulation in wall-clock time (the
reference's real-time behavior: 1 tick = 100 ms at tick_hz=10); with tick_hz=0 the
clock only moves via /step/{k}, which is what tests use.
"""

from __future__ import annotations

import json
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, unquote

from raft_kotlin_tpu.api import opsplane as opsplane_mod
from raft_kotlin_tpu.api.simulator import Simulator

_ROUTE_LOG = re.compile(r"^/(\d+)/(\d+)/?$")
_ROUTE_CMD = re.compile(r"^/(\d+)/(\d+)/cmd/([^/]+)$")
_ROUTE_STATUS = re.compile(r"^/(\d+)/(\d+)/status$")
_ROUTE_FAULT = re.compile(r"^/(\d+)/(\d+)/(crash|restart)$")
_ROUTE_STEP = re.compile(r"^/step/(\d+)$")
_ROUTE_KV_DUMP = re.compile(r"^/(\d+)/kv/?$")
_ROUTE_KV_GET = re.compile(r"^/(\d+)/kv/(\d+)$")
_ROUTE_READ = re.compile(r"^/(\d+)/read/(\d+)$")

MAX_STEP_PER_REQUEST = 100_000


class RaftHTTPServer:
    """Own the ThreadingHTTPServer + optional tick thread; `with` or start()/stop()."""

    def __init__(self, sim: Optional[Simulator], port: int = 7000,
                 tick_hz: float = 0.0, ops=None):
        self.sim = sim
        self.ops = ops  # opsplane.OpsPlane (farm mode) or None
        self.tick_hz = tick_hz
        if sim is None and ops is None:
            raise ValueError("RaftHTTPServer needs a Simulator, an "
                             "OpsPlane snapshot holder, or both")
        self._stop = threading.Event()
        self._tick_thread: Optional[threading.Thread] = None

        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet; observability goes through /status
                pass

            def _send(self, code: int, body: str, ctype="text/plain"):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                sim = outer.sim
                try:
                    # §21 scrape surface — served before the simulator
                    # routes so farm mode (sim=None) can answer them.
                    if self.path in ("/metrics", "/metrics/"):
                        if outer.ops is not None:
                            return self._send(
                                200, outer.ops.prometheus_text(),
                                "text/plain; version=0.0.4")
                        if sim is not None:
                            return self._send(
                                200, opsplane_mod.prometheus_text(
                                    sim.metrics_snapshot()),
                                "text/plain; version=0.0.4")
                    if self.path in ("/events", "/events/"):
                        if outer.ops is not None:
                            return self._send(200, outer.ops.events_json(),
                                              "application/json")
                        return self._send(
                            404, "events need an attached ops plane "
                                 "(farm mode / ops=OpsPlane())")
                    if self.path in ("/healthz", "/healthz/"):
                        if outer.ops is not None:
                            code, body = outer.ops.healthz()
                            return self._send(code, json.dumps(body),
                                              "application/json")
                        snap = sim.metrics_snapshot()
                        bad = snap.get("inv_status", "clean") != "clean"
                        return self._send(
                            503 if bad else 200,
                            json.dumps({
                                "status": "unhealthy" if bad else "ok",
                                "inv_status": snap.get("inv_status",
                                                       "clean"),
                                "tick": snap.get("ticks_total"),
                            }), "application/json")
                    if sim is None:
                        return self._send(
                            503, "farm mode: only /metrics, /events and "
                                 "/healthz respond (no simulator attached)")
                    if self.path in ("", "/"):
                        shown = min(sim.cfg.n_groups, 64)
                        body = json.dumps(
                            {
                                "tick": sim.tick_count,
                                "groups": sim.cfg.n_groups,
                                "nodes_per_group": sim.cfg.n_nodes,
                                "leaders": {
                                    str(g): ls
                                    for g, ls in sim.leaders_all(shown).items()
                                },
                                "leaders_truncated": shown < sim.cfg.n_groups,
                            }
                        )
                        return self._send(200, body, "application/json")
                    m = _ROUTE_CMD.match(self.path)
                    if m:
                        g, n = int(m[1]), int(m[2])
                        raw, _, query = m[3].partition("?")
                        cmd = unquote(raw)
                        params = parse_qs(query)
                        want_async = params.get("async", ["0"])[-1] in ("1", "true")
                        sim.cmd(g, n, cmd)
                        if want_async:
                            return self._send(200, f"Server {n} queued {cmd!r}")
                        # Reference-faithful observable: GET /cmd/{c} appends
                        # synchronously and replies with the full log dump
                        # (RaftServer.kt:87-90). The append lands in phase 0 of
                        # the next tick, so block until that tick has run —
                        # stepping it ourselves on a manual clock, waiting for
                        # the tick thread otherwise — then dump.
                        target = sim.tick_count + 1
                        if outer.tick_hz <= 0:
                            sim.step(1)
                        else:
                            # Generous deadline: the FIRST tick triggers the
                            # JIT compile, which can take minutes on a slow
                            # host — and a silent pre-append dump would break
                            # the reference contract, so time out LOUDLY.
                            deadline = time.monotonic() + max(
                                600.0 if sim.tick_count == 0 else 5.0,
                                3.0 / outer.tick_hz)
                            while (sim.tick_count < target
                                   and time.monotonic() < deadline):
                                time.sleep(min(0.01, 1.0 / outer.tick_hz / 4))
                            if sim.tick_count < target:
                                return self._send(
                                    503,
                                    f"Server {n} queued {cmd!r} but the "
                                    f"delivering tick did not run within the "
                                    f"deadline; retry GET /{g}/{n}/ for the "
                                    f"log dump")
                        ents = sim.entries(g, n)
                        return self._send(200, f"Server {n} log {ents}")
                    m = _ROUTE_LOG.match(self.path)
                    if m:
                        g, n = int(m[1]), int(m[2])
                        ents = sim.entries(g, n)
                        return self._send(200, f"Server {n} log {ents}")
                    m = _ROUTE_STATUS.match(self.path)
                    if m:
                        g, n = int(m[1]), int(m[2])
                        return self._send(
                            200, json.dumps(sim.node_status(g, n)), "application/json"
                        )
                    m = _ROUTE_FAULT.match(self.path)
                    if m:
                        g, n, verb = int(m[1]), int(m[2]), m[3]
                        getattr(sim, verb)(g, n)
                        return self._send(200, f"Server {n} {verb} queued")
                    m = _ROUTE_KV_GET.match(self.path)
                    if m:
                        g, s = int(m[1]), int(m[2])
                        return self._send(200, json.dumps(sim.kv_get(g, s)),
                                          "application/json")
                    m = _ROUTE_KV_DUMP.match(self.path)
                    if m:
                        g = int(m[1])
                        return self._send(200, json.dumps(sim.kv_dump(g)),
                                          "application/json")
                    m = _ROUTE_READ.match(self.path)
                    if m:
                        g, s = int(m[1]), int(m[2])
                        out = sim.read(g, s)
                        # A read that cannot be served THIS tick is not an
                        # error — it is the §20 queue saying "retry": 503.
                        code = 200 if out["ok"] else 503
                        return self._send(code, json.dumps(out),
                                          "application/json")
                    if self.path in ("/serving", "/serving/"):
                        return self._send(200, json.dumps(sim.serving_stats()),
                                          "application/json")
                    m = _ROUTE_STEP.match(self.path)
                    if m:
                        k = int(m[1])
                        if k > MAX_STEP_PER_REQUEST:
                            return self._send(
                                400, f"step > {MAX_STEP_PER_REQUEST}; split the request"
                            )
                        # One tick per lock hold so concurrent routes (and the tick
                        # thread) interleave instead of stalling behind a long step.
                        for _ in range(k):
                            sim.step(1)
                        return self._send(200, json.dumps({"tick": sim.tick_count}),
                                          "application/json")
                    return self._send(404, "not found")
                except IndexError as e:
                    return self._send(400, str(e))

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._httpd.server_address[1]

    def start(self):
        threading.Thread(target=self._httpd.serve_forever, daemon=True).start()
        if self.tick_hz > 0:
            period = 1.0 / self.tick_hz

            def loop():
                while not self._stop.is_set():
                    t0 = time.monotonic()
                    self.sim.step(1)
                    self._stop.wait(max(0.0, period - (time.monotonic() - t0)))

            self._tick_thread = threading.Thread(target=loop, daemon=True)
            self._tick_thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._tick_thread is not None:
            self._tick_thread.join(timeout=5)
        self._httpd.shutdown()
        self._httpd.server_close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
