"""§21 streaming ops plane — host side (SEMANTICS.md §21).

The device half of the ops plane lives in the monitor carry
(utils/telemetry.py: the (W, K) series ring + the bounded event ring,
bit-neutral reductions over state-transition pairs). This module is the
HOST half:

- `SLOSpec` — a declarative service-level objective over the §19/§20
  farm metrics (read p99, downtime fraction, election p90, farm_util
  floor), evaluated PER SEGMENT with error-budget burn accounting
  (`SLOBurn`): a segment that misses any gated dimension consumes
  budget; burn = violated_fraction / budget_frac, breach at burn >= 1.
  `slo_status` is "clean" or "breach:<dim>@seg<k>" — the same
  clean/non-clean shape every inv_status-style field uses, so
  summarize_bench's INV_LEGS machinery gates it unchanged.
- `prometheus_text` — render one farm snapshot as Prometheus text
  exposition (the `GET /metrics` body).
- `OpsPlane` — a thread-safe snapshot holder between the farm loop
  (producer: api/fuzz.continuous_farm's per-segment `publish`) and the
  HTTP scrape surface (consumer: api/http_api.py's /metrics, /events,
  /healthz). The farm already materializes one host-side readback set
  per segment; `update` stores THAT dict, so scrapes are pure host
  reads — zero extra device syncs, however often Prometheus polls.
"""

from __future__ import annotations

import dataclasses
import json
import threading
from typing import Optional

# The gated dimensions, in evaluation (and breach-report) order:
# (spec field, snapshot key, cmp) — cmp "max" gates value <= bound,
# "min" gates value >= bound.
SLO_DIMS = (
    ("read_p99_ticks", "read_p99", "max"),
    ("downtime_frac_max", "downtime_frac", "max"),
    ("election_p90_ticks", "election_p90", "max"),
    ("farm_util_min", "farm_util", "min"),
)


@dataclasses.dataclass(frozen=True)
class SLOSpec:
    """A declarative SLO over per-segment farm metrics. None disables a
    dimension (ungated). `budget_frac` is the error budget: the fraction
    of segments allowed to miss before the SLO counts as breached —
    burn-rate accounting, not instant failure, so one bad segment in a
    long soak spends budget instead of tripping the farm."""

    read_p99_ticks: Optional[int] = None
    downtime_frac_max: Optional[float] = None
    election_p90_ticks: Optional[int] = None
    farm_util_min: Optional[float] = None
    budget_frac: float = 0.1

    def __post_init__(self):
        if not (0.0 < self.budget_frac <= 1.0):
            raise ValueError("budget_frac must be in (0, 1]")

    @property
    def gated_dims(self) -> tuple:
        return tuple(f for f, _, _ in SLO_DIMS
                     if getattr(self, f) is not None)

    def violated_dims(self, metrics: dict) -> list:
        """The gated dimensions this segment's metrics miss (snapshot-key
        names, evaluation order). A metric absent from `metrics` (e.g.
        read_p99 on a serving-off farm) cannot violate."""
        out = []
        for field, key, cmp in SLO_DIMS:
            bound = getattr(self, field)
            if bound is None or metrics.get(key) is None:
                continue
            v = metrics[key]
            if (v > bound) if cmp == "max" else (v < bound):
                out.append(key)
        return out


class SLOBurn:
    """Error-budget burn accounting over a segment stream: feed each
    segment's metrics, read burn / status. First-breach coordinate is
    sticky (the latch idiom), burn itself keeps updating."""

    def __init__(self, slo: SLOSpec):
        self.slo = slo
        self.segments = 0
        self.violated_segments = 0
        self.by_dim: dict = {}
        self.first_breach: Optional[tuple] = None  # (dim, segment)

    def observe(self, metrics: dict) -> list:
        """Fold one segment; returns its violated dims."""
        dims = self.slo.violated_dims(metrics)
        seg = self.segments
        self.segments += 1
        if dims:
            self.violated_segments += 1
            for d in dims:
                self.by_dim[d] = self.by_dim.get(d, 0) + 1
        if self.first_breach is None and dims and self.burn >= 1.0:
            self.first_breach = (dims[0], seg)
        return dims

    @property
    def burn(self) -> float:
        """violated_fraction / budget_frac — >= 1.0 means the error
        budget is spent (breach)."""
        if not self.segments:
            return 0.0
        frac = self.violated_segments / self.segments
        return frac / self.slo.budget_frac

    @property
    def breached(self) -> bool:
        return self.first_breach is not None

    @property
    def status(self) -> str:
        """"clean" or "breach:<dim>@seg<k>" — plugs into the INV_LEGS
        non-clean => exit-1 machinery by shape."""
        if self.first_breach is None:
            return "clean"
        dim, seg = self.first_breach
        return f"breach:{dim}@seg{seg}"

    def as_dict(self) -> dict:
        return {"status": self.status, "burn": self.burn,
                "segments": self.segments,
                "violated_segments": self.violated_segments,
                "by_dim": dict(self.by_dim)}


def _prom_val(v) -> str:
    return repr(float(v)) if isinstance(v, float) else str(int(v))


def prometheus_text(snap: dict) -> str:
    """Render one farm snapshot dict as Prometheus text exposition
    (version 0.0.4). Scalars become raft_<key>; the telemetry counter
    dict becomes raft_tel_<counter>_total; the latest series window
    becomes raft_series{channel="..."} gauges. Pure host formatting over
    the snapshot the farm loop already materialized — never touches the
    device."""
    lines = []

    def emit(name, v, kind="gauge", help_=None):
        if help_:
            lines.append(f"# HELP {name} {help_}")
        lines.append(f"# TYPE {name} {kind}")
        lines.append(f"{name} {_prom_val(v)}")

    for key, kind in (("segment", "counter"), ("ticks_total", "counter"),
                      ("universes_admitted", "counter"),
                      ("universes_retired", "counter"),
                      ("events_dropped", "counter"),
                      ("farm_util", "gauge"), ("downtime_frac", "gauge"),
                      ("election_p90", "gauge"), ("read_p99", "gauge"),
                      ("slo_burn", "gauge")):
        if snap.get(key) is not None:
            emit(f"raft_{key}", snap[key], kind)
    if "inv_status" in snap:
        emit("raft_inv_clean", 0 if snap["inv_status"] != "clean" else 1,
             help_="1 while the invariant monitor has never latched")
    if "slo_status" in snap:
        emit("raft_slo_breached", 1 if snap["slo_status"] != "clean" else 0)
    tel = snap.get("telemetry") or {}
    for k in sorted(tel):
        emit(f"raft_tel_{k}_total", tel[k], "counter")
    # Generic passthrough for producer-specific gauges (the Simulator's
    # interactive snapshot uses this for leader coverage / §20 totals).
    gauges = snap.get("gauges") or {}
    for k in sorted(gauges):
        emit(f"raft_{k}", gauges[k], "gauge")
    series = snap.get("series")
    if series and series.get("windows"):
        last = series["windows"][-1]
        lines.append("# TYPE raft_series gauge")
        for ch in series["names"]:
            lines.append('raft_series{channel="%s"} %s'
                         % (ch, _prom_val(last[ch])))
    return "\n".join(lines) + "\n"


class OpsPlane:
    """Thread-safe snapshot holder between the farm loop and the HTTP
    scrape surface. The producer calls update(snapshot) once per segment
    (api/fuzz.continuous_farm's `publish` hook does exactly this);
    consumers read rendered views. All consumer paths are lock-guarded
    host reads of the LAST published snapshot — no device handle ever
    enters this object."""

    def __init__(self):
        self._lock = threading.Lock()
        self._snap: Optional[dict] = None

    def update(self, snap: dict) -> None:
        with self._lock:
            self._snap = dict(snap)

    def snapshot(self) -> Optional[dict]:
        with self._lock:
            return dict(self._snap) if self._snap is not None else None

    def prometheus_text(self) -> str:
        snap = self.snapshot()
        return prometheus_text(snap) if snap else "# no snapshot yet\n"

    def events_json(self) -> str:
        snap = self.snapshot() or {}
        return json.dumps({"events": snap.get("events") or [],
                           "events_dropped": snap.get("events_dropped", 0),
                           "segment": snap.get("segment")})

    def healthz(self) -> tuple:
        """(http_status, body): 200 while the monitor and the SLO are
        clean, 503 on a latched invariant or a breached SLO, 200 with
        "starting" before the first snapshot."""
        snap = self.snapshot()
        if snap is None:
            return 200, {"status": "starting"}
        bad = (snap.get("inv_status", "clean") != "clean"
               or snap.get("slo_status", "clean") != "clean")
        body = {"status": "unhealthy" if bad else "ok",
                "inv_status": snap.get("inv_status", "clean"),
                "slo_status": snap.get("slo_status", "clean"),
                "segment": snap.get("segment")}
        return (503 if bad else 200), body
