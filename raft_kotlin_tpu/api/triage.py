"""Automatic divergence triage (ISSUE 5): localize a TPU-vs-oracle
bit-exactness failure to the first divergent (tick, group) and hand back
everything a human needs to read it. Extended (ISSUE 6) with SAFETY
triage: a violation latched by the on-device invariant monitor
(utils/telemetry — the earliest (tick, group, invariant_id) of a run) is
replayed deterministically, re-bisected to the same coordinate, and
rendered as a replayable (seed, config, tick, group) tuple with the
explain() narrative attached (`triage_violation` below — bench.py
auto-invokes it on any leg whose inv_status is not clean).

PARITY.md makes bit-exactness against the scalar oracle the project's core
contract, and the differential suites enforce it — but when a parity leg
FAILS, the artifact so far has been a one-line "field X diverges first at
tick=.. group=.." string (native/oracle.trace_parity). This module is the
mechanical follow-through:

1. **Bisect** — `find_divergence` compares the full per-tick trace
   matrices and returns the lexicographically FIRST divergent
   (tick, group) with every field that disagrees there. The traces are
   already materialized arrays, so the "bisection" is one vectorized
   argmax over the mismatch mask — exact, no re-execution.
2. **Dump** — `triage` attaches both sides' complete per-node trace rows
   at the divergent tick AND the tick before it (the last agreeing
   state), so the transition that broke is visible without re-running
   anything.
3. **Explain** — the [tick - window, tick + window] narrative of the
   divergent group rendered through api/explain.explain() (the oracle
   replay with the event sink on): what the canonical serialization says
   SHOULD have happened around the break.

bench.py runs this automatically whenever a parity stage reports < 1.0
and publishes a compact `triage_status` in the headline tail
("clean" / "field@t<tick>/g<group>"), so the authoritative artifact
records not just THAT parity broke but WHERE.

Layout conventions match native/oracle.trace_parity: kernel traces are
(T, N, G) groups-minor dicts (ops/tick.make_run(trace=True)); oracle
traces are (T, G, N) int32 dicts (native.oracle.NativeOracle.run).
"""

from __future__ import annotations

import sys
from typing import Dict, Optional, TextIO

import numpy as np

from raft_kotlin_tpu.native.oracle import TRACE_FIELDS
from raft_kotlin_tpu.utils.config import RaftConfig


def find_divergence(ktr: Dict, otr: Dict) -> Optional[dict]:
    """First divergent (tick, group) between a kernel trace `ktr`
    ((T, N, G) groups-minor) and an oracle trace `otr` ((T, G, N)).

    Returns None when every TRACE_FIELDS array bit-matches; otherwise
    {"tick", "group", "fields", "kernel", "oracle"} where `fields` lists
    every divergent field at that (tick, group) — commitIndex mismatches
    (the ISSUE-5 headline case) surface here like any other field — and
    kernel/oracle carry the full per-node rows of EVERY trace field there.
    "First" is lexicographic (tick, then group): the earliest tick with
    any mismatch, and within it the lowest group id — the canonical
    bisection target (everything before it agrees bit-for-bit).
    """
    fields = [k for k in TRACE_FIELDS if k in ktr and k in otr]
    assert fields, "no shared trace fields to compare"
    kv = {k: np.asarray(ktr[k]).transpose(0, 2, 1).astype(np.int64)
          for k in fields}  # (T, G, N)
    ov = {k: np.asarray(otr[k]).astype(np.int64) for k in fields}
    bad = None  # (T, G): any field/node mismatch
    for k in fields:
        neq = (kv[k] != ov[k]).any(axis=2)
        bad = neq if bad is None else (bad | neq)
    if not bad.any():
        return None
    bad_tick = bad.any(axis=1)
    t = int(np.argmax(bad_tick))        # first True (argmax on bool)
    g = int(np.argmax(bad[t]))
    div_fields = [k for k in fields if (kv[k][t, g] != ov[k][t, g]).any()]
    return {
        "tick": t,
        "group": g,
        "fields": div_fields,
        "kernel": {k: kv[k][t, g].tolist() for k in fields},
        "oracle": {k: ov[k][t, g].tolist() for k in fields},
    }


def triage_status(div: Optional[dict]) -> str:
    """The compact one-token form bench.py's headline tail publishes."""
    if div is None:
        return "clean"
    return f"{div['fields'][0]}@t{div['tick']}/g{div['group']}"


def triage(cfg: RaftConfig, n_ticks: Optional[int] = None,
           ktr: Optional[Dict] = None, otr: Optional[Dict] = None,
           window: int = 8, impl: str = "xla",
           out: Optional[TextIO] = None) -> Optional[dict]:
    """Full divergence triage for `cfg`: bisect, dump, explain.

    `ktr`/`otr` may be supplied (e.g. bench.py's parity stage already holds
    both); missing sides are produced here — the kernel via
    ops/tick.make_run(trace=True, impl=impl), the oracle via the native C++
    engine (bit-identical to the Python oracle by the differential suites).
    `n_ticks` is required when a side must be produced; otherwise it is
    read off the supplied traces.

    Returns None when the traces bit-match. On divergence returns the
    find_divergence dict extended with:
    - "prev_kernel"/"prev_oracle": both sides' full rows at tick - 1 (the
      last agreeing state; absent at tick 0),
    - "explain_window": (lo, hi) tick bounds of the rendered narrative,
    - "explain_events": the oracle event dicts in that window,
    - "explain_text": the formatted narrative (api/explain.format_event),
    and prints a human-readable report to `out` (None = no printing;
    bench.py passes sys.stderr).
    """
    if ktr is None:
        from raft_kotlin_tpu.models.state import init_state
        from raft_kotlin_tpu.ops.tick import make_run

        assert n_ticks is not None, "n_ticks needed to produce the kernel trace"
        _, ktr = make_run(cfg, n_ticks, trace=True, impl=impl)(init_state(cfg))
    if otr is None:
        from raft_kotlin_tpu.native.oracle import NativeOracle

        T = n_ticks if n_ticks is not None \
            else np.asarray(next(iter(ktr.values()))).shape[0]
        otr = NativeOracle(cfg).run(int(T))

    div = find_divergence(ktr, otr)
    if div is None:
        return None
    t, g = div["tick"], div["group"]
    if t > 0:
        kv = {k: np.asarray(ktr[k])[t - 1, :, g].tolist() for k in div["kernel"]}
        ovp = {k: np.asarray(otr[k])[t - 1, g].tolist() for k in div["oracle"]}
        div["prev_kernel"], div["prev_oracle"] = kv, ovp

    from raft_kotlin_tpu.api.explain import explain_text

    lo, hi = max(0, t - window), t + window
    try:
        events, text = explain_text(cfg, g, lo, hi)
    except Exception as e:  # the report must survive a replay failure
        events, text = [], f"(explain replay failed: {e})"
    div["explain_window"] = (lo, hi)
    div["explain_events"] = events
    div["explain_text"] = text

    if out is not None:
        print(format_report(div), file=out)
    return div


def triage_violation(cfg: RaftConfig, latch: dict,
                     window: int = 8, replay: bool = True,
                     state0=None, rng_seed: Optional[int] = None,
                     out: Optional[TextIO] = None) -> dict:
    """Safety-violation triage (ISSUE 6): turn an on-device monitor latch
    into a replayable, human-readable artifact.

    `latch` is the monitor's first-violation coordinate — {"tick",
    "group", "invariant_id" or "invariant"} (summarize_monitor's latch
    dict, or the inv_latch_* scalars bench collects). Returns a dict:

    - "seed"/"rng_seed"/"config"/"tick"/"group"/"invariant"/
      "invariant_id": the replayable tuple — `make_run(
      RaftConfig(**config), tick+1, monitor=True, rng=make_rng(
      replace(cfg, seed=rng_seed)))` from init_state re-latches the same
      coordinate (counted-threefry determinism: same seeds + config =>
      same bits => same verdicts). `rng_seed` (default cfg.seed) covers
      bench.measure's reps, which run the cfg-seeded INITIAL state under
      a per-rep perturbed rng OPERAND — the replay must reproduce
      exactly that split or it diverges from tick 0 (init_state's boot
      election draws are seed-dependent).
    - "confirmed"/"replay_latch" (replay=True): the device replay was
      actually performed here, through ops/tick.make_run(monitor=True)
      over tick+1 ticks, and its latch compared against `latch` — the
      bisection check. `state0` overrides the replay's initial state
      (injected-violation tests start from a corrupted state that
      init_state cannot reproduce).
    - "explain_window"/"explain_events"/"explain_text": the
      [tick - window, tick + window] oracle narrative of the latched
      group (api/explain), same attachment as the parity triage.

    Prints format_violation_report to `out` (None = no printing)."""
    from raft_kotlin_tpu.utils.telemetry import INVARIANT_IDS

    t, g = int(latch["tick"]), int(latch["group"])
    iid = latch.get("invariant_id")
    if iid is None:
        inv = latch.get("invariant", latch.get("inv"))
        iid = INVARIANT_IDS.index(inv) if isinstance(inv, str) else inv
    iid = int(iid)
    name = INVARIANT_IDS[iid] if 0 <= iid < len(INVARIANT_IDS) \
        else str(latch.get("invariant"))
    import dataclasses

    rng_seed = cfg.seed if rng_seed is None else int(rng_seed)
    rec = {
        "seed": cfg.seed,
        "rng_seed": rng_seed,
        "config": dataclasses.asdict(cfg),
        "tick": t,
        "group": g,
        "invariant": name,
        "invariant_id": iid,
        "status": f"{name}@t{t}/g{g}",
    }
    if replay:
        from raft_kotlin_tpu.models.state import init_state
        from raft_kotlin_tpu.ops.tick import make_rng, make_run
        from raft_kotlin_tpu.utils.telemetry import summarize_monitor

        st0 = state0 if state0 is not None else init_state(cfg)
        rng = (make_rng(dataclasses.replace(cfg, seed=rng_seed))
               if rng_seed != cfg.seed else None)
        # The repo-wide CPU guard for deep configs: XLA:CPU compiles of
        # the batched deep engine blow up (ops/tick.py), so the replay
        # uses the bit-identical per-pair engine there — same verdicts.
        import jax

        batched = (False if (cfg.uses_dyn_log
                             and jax.default_backend() == "cpu") else None)
        try:
            *_, mon = make_run(cfg, t + 1, trace=False, monitor=True,
                               batched=batched, rng=rng)(st0)
            rl = summarize_monitor(mon)["latch"]
            rec["replay_latch"] = rl
            rec["confirmed"] = (rl is not None and rl["tick"] == t
                                and rl["group"] == g
                                and rl["invariant_id"] == iid)
        except Exception as e:  # the report must survive a replay failure
            rec["replay_latch"] = None
            rec["confirmed"] = False
            rec["replay_error"] = str(e)[:200]

    from raft_kotlin_tpu.api.explain import explain_text

    lo, hi = max(0, t - window), t + window
    try:
        events, text = explain_text(cfg, g, lo, hi)
    except Exception as e:  # ditto
        events, text = [], f"(explain replay failed: {e})"
    rec["explain_window"] = (lo, hi)
    rec["explain_events"] = events
    rec["explain_text"] = text

    if out is not None:
        print(format_violation_report(rec), file=out)
    return rec


def format_violation_report(rec: dict) -> str:
    """Human-readable safety-triage report (stderr artifact, like
    format_report — the stdout JSON contract stays intact)."""
    t, g = rec["tick"], rec["group"]
    rng_note = ("" if rec.get("rng_seed", rec["seed"]) == rec["seed"]
                else f" rng_seed={rec['rng_seed']} (perturbed rng operand"
                " over the cfg-seeded initial state — bench.measure's"
                " per-rep split)")
    lines = [
        f"=== SAFETY TRIAGE: {rec['invariant']} violated first at "
        f"tick={t} group={g} ===",
        f"replay tuple: seed={rec['seed']}{rng_note} tick={t} group={g} "
        f"invariant={rec['invariant']} (config in the record; "
        f"make_run(cfg, {t + 1}, monitor=True) re-latches it)",
    ]
    if "confirmed" in rec:
        lines.append(
            f"replay bisection: confirmed={rec['confirmed']} "
            f"(replay latch: {rec.get('replay_latch')})")
        if rec.get("replay_error"):
            lines.append(f"replay error: {rec['replay_error']}")
    lo, hi = rec["explain_window"]
    lines.append(f"oracle narrative for group {g}, ticks {lo}..{hi}:")
    lines.append(rec["explain_text"].rstrip())
    return "\n".join(lines)


def format_report(div: dict) -> str:
    """Human-readable triage report (one string; bench.py sends it to
    stderr so the stdout JSON contract stays intact)."""
    t, g = div["tick"], div["group"]
    lines = [
        f"=== TRIAGE: first divergence at tick={t} group={g} "
        f"(fields: {', '.join(div['fields'])}) ===",
        "state at the divergent tick (per node, kernel vs oracle):",
    ]
    for k in div["kernel"]:
        mark = "  <-- DIVERGES" if k in div["fields"] else ""
        lines.append(f"  {k:>11}: kernel={div['kernel'][k]} "
                     f"oracle={div['oracle'][k]}{mark}")
    if "prev_kernel" in div:
        lines.append(f"last agreeing state (tick {t - 1}):")
        for k in div["prev_kernel"]:
            lines.append(f"  {k:>11}: {div['prev_kernel'][k]}")
    lo, hi = div["explain_window"]
    lines.append(f"oracle narrative for group {g}, ticks {lo}..{hi}:")
    lines.append(div["explain_text"].rstrip())
    return "\n".join(lines)
