from raft_kotlin_tpu.api.simulator import Simulator
from raft_kotlin_tpu.api.http_api import RaftHTTPServer

__all__ = ["Simulator", "RaftHTTPServer"]
