"""Simulator: the user-facing driver around the vectorized tick.

This is the L4 of the rebuild (SURVEY.md §1): where the reference exposes a per-node
HTTP API — `GET /` dumps the log, `GET /cmd/{command}` appends a command locally with
no leader check (reference RaftServer.kt:72-107) — the simulator exposes the same two
verbs addressed by (group, node): `entries(g, n)` and `cmd(g, n, command)`. Commands
are strings at this layer, interned to vocabulary ids before they enter the
kernel (SEMANTICS.md §2), and de-interned on the way out. int32 logs get the
unbounded 1<<30-based id space; int16 logs (the deep config-5 band) get a
BOUNDED 16384-id vocabulary at 1<<14 (capacity-checked), so the HTTP surface
can drive deep simulations too (VERDICT r5 weak #6).

Injected commands are queued host-side and delivered in phase 0 of the NEXT tick via
the kernel's `inject` argument (ops/tick.py) — the discretized equivalent of an HTTP
write landing between protocol events.

Serving configs (cfg.serve_slots > 0, SEMANTICS.md §20) additionally carry
the applied KV state machine: step() advances the serving carry on every
post-tick state, `kv_get`/`kv_dump` read the applied store, and `read` is
the log-free linearizable read (served only under the config's read_path
leadership-confirmation rule; a blocked read returns ok=False and the
caller retries — the HTTP layer maps that to 503).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_kotlin_tpu.constants import FOLLOWER, CANDIDATE, LEADER  # noqa: F401
from raft_kotlin_tpu.models.state import RaftState, init_state
from raft_kotlin_tpu.ops.tick import make_tick
from raft_kotlin_tpu.utils.config import RaftConfig

_NO_CMD = -1

# Interned user-command ids live above this base so they can never collide with the
# cmd_period workload's raw tick values (ops/tick.py phase 0 writes cmd = tick index).
INTERN_BASE = 1 << 30

# int16 logs (VERDICT r5 weak #6): ids live in [1 << 14, 2^15) — a BOUNDED
# vocabulary of 16384 commands that fits the narrow storage dtype, so the L4
# API can drive the deep config-5 band (log_dtype="int16"). The same
# no-collision argument holds as long as cmd_period tick values stay below
# 1 << 14 — and an int16 run past 16384 ticks was already outside the dtype's
# documented envelope (utils/config.log_dtype: stored commands must fit;
# the cmd_period workload stores the tick index). intern() raises once the
# capacity is exhausted rather than silently wrapping into workload space.
INTERN_BASE16 = 1 << 14
VOCAB_CAP16 = (1 << 15) - INTERN_BASE16  # 16384 interned commands


class Simulator:
    """One live simulation: all groups x nodes, stepped on demand.

    Thread-safe: every public method takes the instance lock, so an HTTP frontend
    (api/http_api.py) and a background tick loop can share one Simulator.
    """

    def __init__(self, cfg: RaftConfig, state: Optional[RaftState] = None,
                 impl: str = "auto"):
        """impl: "xla", "pallas" (ops/pallas_tick.py megakernel), or "auto" —
        pallas when running on an accelerator with a lane-aligned group count,
        else xla. Both backends are bit-identical (shared phase_body)."""
        # log_dtype="int16" (the deep config-5 band) switches to the bounded
        # 16384-id vocabulary at INTERN_BASE16; int32 keeps the unbounded
        # 1<<30 base. Either way ids never collide with cmd_period's raw
        # tick values within the dtype's documented envelope.
        self._intern_base = (INTERN_BASE16 if cfg.log_dtype == "int16"
                             else INTERN_BASE)
        self._vocab_cap = (VOCAB_CAP16 if cfg.log_dtype == "int16"
                           else None)
        self.cfg = cfg
        self._lock = threading.RLock()
        self._state = state if state is not None else init_state(cfg)
        auto = impl == "auto"
        if auto:
            from raft_kotlin_tpu.ops.pallas_tick import choose_impl

            impl = choose_impl(cfg)
        if impl == "pallas":
            from raft_kotlin_tpu.ops.pallas_tick import make_pallas_tick

            tick = make_pallas_tick(cfg)
        else:
            tick = make_tick(cfg)
        # One jitted callable; None-ness of the optional args is static, so each of
        # the four (inject?, fault_cmd?) combinations traces once and is cached.
        # The rng tuple is passed per call (a jit operand, not a baked constant)
        # so the compiled tick is seed-independent — see ops.tick.make_rng.
        from raft_kotlin_tpu.ops.tick import make_rng

        self._rng = make_rng(cfg)
        self._tick = jax.jit(tick)
        if auto and impl == "pallas":
            # choose_impl validates tile construction only; Mosaic compiles lazily
            # at the first step. step() can present any of the FOUR (inject?,
            # fault_cmd?) presence combinations (e.g. a first /cmd with no
            # pending fault is inject-only), and each is a distinct BodyFlags
            # variant — a distinct Mosaic kernel. Warm ALL four so a config
            # passing the VMEM heuristic but rejected by Mosaic for any variant
            # falls back to the XLA tick here instead of crashing at the first
            # /cmd or crash()/restart() (results discarded).
            try:
                no_cmd = jnp.full((cfg.n_groups, cfg.n_nodes), _NO_CMD,
                                  dtype=jnp.int32)
                no_fault = jnp.zeros((cfg.n_groups, cfg.n_nodes), dtype=jnp.int32)
                for args in ((no_cmd, no_fault), (no_cmd, None),
                             (None, no_fault), (None, None)):
                    jax.block_until_ready(
                        self._tick(self._state, *args, rng=self._rng).term)
            except Exception:
                impl = "xla"
                self._tick = jax.jit(make_tick(cfg))
        self.impl = impl
        # §20 serving carry: advanced after every tick; None for serve_slots=0
        # configs (the serving path compiles out entirely).
        from raft_kotlin_tpu.ops import serving as serving_mod

        self._srv = serving_mod.serving_init(cfg)
        if self._srv is not None:
            from raft_kotlin_tpu.ops.tick import split_rng
            from raft_kotlin_tpu.utils import rng as rngmod

            def _sstep(state, srv, rng):
                base, _tk, _bk, scen = split_rng(rng)
                kw = rngmod.kt_key_words(base)
                return serving_mod.serving_step(
                    cfg, serving_mod.serving_view(state), srv, kw=kw,
                    scen=scen)

            self._srv_step = jax.jit(_sstep)
        # Pending phase-0 injections for the next tick: {(g, n): cmd_id} — last write
        # wins per (group, node), like back-to-back HTTP posts within one tick window.
        self._pending: Dict[Tuple[int, int], int] = {}
        # Pending phase-F fault commands for the next tick: {(g, n): 1 crash | 2 restart}.
        self._pending_faults: Dict[Tuple[int, int], int] = {}
        # Command vocabulary: string <-> int32 id (ids start at 0; -1 = none).
        self._vocab: Dict[str, int] = {}
        self._rvocab: List[str] = []

    # -- vocabulary -----------------------------------------------------------

    def intern(self, command: str) -> int:
        with self._lock:
            if command not in self._vocab:
                if (self._vocab_cap is not None
                        and len(self._rvocab) >= self._vocab_cap):
                    raise ValueError(
                        f"int16 vocabulary full ({self._vocab_cap} distinct "
                        "commands): narrow logs bound the id space — use "
                        "log_dtype='int32' for unbounded vocabularies")
                self._vocab[command] = self._intern_base + len(self._rvocab)
                self._rvocab.append(command)
            return self._vocab[command]

    def command_name(self, cmd_id: int) -> str:
        with self._lock:
            k = cmd_id - self._intern_base
            if 0 <= k < len(self._rvocab):
                return self._rvocab[k]
            return str(cmd_id)  # ids injected by cmd_period workload are raw ticks

    # -- the two reference verbs ---------------------------------------------

    def cmd(self, group: int, node: int, command: str) -> int:
        """Queue `command` for (group, node) — lands in its LOCAL log next tick at its
        LOCAL term, exactly like the reference's GET /cmd/{command}
        (RaftServer.kt:100-107: no leader check, no redirect, no quorum wait)."""
        self._check_addr(group, node)
        cid = self.intern(command)
        with self._lock:
            self._pending[(group, node)] = cid
        return cid

    def entries(self, group: int, node: int) -> List[Tuple[int, str]]:
        """The readable log window of (group, node): [(term, command), ...] —
        the reference's GET / dump (RaftServer.kt:84-86, 96-97)."""
        self._check_addr(group, node)
        with self._lock:
            st = self._state
            li = int(st.last_index[node - 1, group])
            terms = np.asarray(st.log_term[node - 1, :li, group])
            cmds = np.asarray(st.log_cmd[node - 1, :li, group])
        return [(int(t), self.command_name(int(c))) for t, c in zip(terms, cmds)]

    # -- stepping -------------------------------------------------------------

    def crash(self, group: int, node: int) -> None:
        """Kill (group, node) at the next tick (SEMANTICS.md §9 phase F): it stops
        participating until restart(); peers see only swallowed RPC failures, exactly
        like a dead process in the reference (RaftServer.kt:170-172)."""
        self._check_addr(group, node)
        with self._lock:
            self._pending_faults[(group, node)] = 1

    def restart(self, group: int, node: int) -> None:
        """Restart a crashed (group, node) at the next tick: it rejoins with ALL state
        wiped (term 0, empty log — reference quirk l, RaftServer.kt:35-48)."""
        self._check_addr(group, node)
        with self._lock:
            self._pending_faults[(group, node)] = 2

    def step(self, n_ticks: int = 1) -> None:
        # Lock per tick, not per call: step(10_000) from a background clock must not
        # starve HTTP readers for the whole multi-tick loop.
        for _ in range(n_ticks):
            with self._lock:
                inject = fault_cmd = None
                if self._pending:
                    arr = np.full(
                        (self.cfg.n_groups, self.cfg.n_nodes), _NO_CMD, dtype=np.int32
                    )
                    for (g, n), cid in self._pending.items():
                        arr[g, n - 1] = cid
                    self._pending.clear()
                    inject = jnp.asarray(arr)
                if self._pending_faults:
                    arr = np.zeros((self.cfg.n_groups, self.cfg.n_nodes), dtype=np.int32)
                    for (g, n), ev in self._pending_faults.items():
                        arr[g, n - 1] = ev
                    self._pending_faults.clear()
                    fault_cmd = jnp.asarray(arr)
                self._state = self._tick(self._state, inject, fault_cmd,
                                         rng=self._rng)
                if self._srv is not None:
                    self._srv = self._srv_step(self._state, self._srv,
                                               self._rng)

    # -- introspection --------------------------------------------------------

    @property
    def tick_count(self) -> int:
        with self._lock:
            return int(self._state.tick)

    @property
    def state(self) -> RaftState:
        with self._lock:
            return self._state

    def node_status(self, group: int, node: int) -> dict:
        self._check_addr(group, node)
        with self._lock:
            st = self._state
            i = node - 1
            return {
                "group": group,
                "node": node,
                "up": bool(st.up[i, group]),
                "role": ["FOLLOWER", "CANDIDATE", "LEADER"][int(st.role[i, group])],
                "term": int(st.term[i, group]),
                "voted_for": int(st.voted_for[i, group]),
                "commit": int(st.commit[i, group]),
                "last_index": int(st.last_index[i, group]),
                "tick": int(st.tick),
            }

    def leaders(self, group: int) -> List[int]:
        """Node ids currently LEADER in `group` (normally 0 or 1 of them)."""
        self._check_addr(group, 1)
        with self._lock:
            roles = np.asarray(self._state.role[:, group])
        return [int(i) + 1 for i in np.nonzero(roles == LEADER)[0]]

    def leaders_all(self, max_groups: Optional[int] = None) -> Dict[int, List[int]]:
        """{group: [leader node ids]} in ONE lock hold / device read."""
        with self._lock:
            roles = np.asarray(self._state.role)  # (N, G)
        ng = roles.shape[1] if max_groups is None else min(roles.shape[1], max_groups)
        return {
            g: [int(i) + 1 for i in np.nonzero(roles[:, g] == LEADER)[0]]
            for g in range(ng)
        }

    # -- §20 serving: applied KV store + log-free linearizable reads ----------

    def _check_serving(self) -> None:
        if self._srv is None:
            raise IndexError(
                "serving path disabled (cfg.serve_slots == 0): construct the "
                "Simulator with a serve_slots > 0 config to get the applied "
                "KV store")

    def _check_slot(self, slot: int) -> None:
        if not (0 <= slot < self.cfg.serve_slots):
            raise IndexError(
                f"slot {slot} out of range [0, {self.cfg.serve_slots})")

    def kv_get(self, group: int, slot: int) -> dict:
        """Applied-store read of one (group, slot): value + monotone version.
        This is the RAW applied view — no leadership check — i.e. a stale read
        in Raft terms. Use read() for the linearizable verb."""
        self._check_serving()
        self._check_addr(group, 1)
        self._check_slot(slot)
        with self._lock:
            val = int(self._srv["kv_val"][slot, group])
            ver = int(self._srv["kv_ver"][slot, group])
        return {"group": group, "slot": slot, "value": val, "version": ver,
                "command": self.command_name(val)}

    def kv_dump(self, group: int) -> dict:
        """Whole applied store of one group in ONE lock hold / device read."""
        self._check_serving()
        self._check_addr(group, 1)
        with self._lock:
            vals = np.asarray(self._srv["kv_val"][:, group])
            vers = np.asarray(self._srv["kv_ver"][:, group])
            applied = int(self._srv["applied"][group])
        return {
            "group": group,
            "applied": applied,
            "slots": [{"slot": s, "value": int(vals[s]), "version": int(vers[s])}
                      for s in range(self.cfg.serve_slots)],
        }

    def read(self, group: int, slot: int) -> dict:
        """Log-free linearizable read (SEMANTICS.md §20): served only when the
        group has a confirmed leader under cfg.read_path — readindex needs a
        live LEADER, lease additionally needs its heartbeat lease armed
        (hb_armed). Returns ok=False when the read cannot be served this tick
        (election in progress / lease lapsed); the caller retries after the
        next tick, exactly like the in-carry read queue."""
        self._check_serving()
        self._check_addr(group, 1)
        self._check_slot(slot)
        from raft_kotlin_tpu.ops.serving import READ_L0

        with self._lock:
            st = self._state
            lead = (np.asarray(st.role[:, group]) == LEADER) & (
                np.asarray(st.up[:, group]) != 0)
            if self.cfg.read_path == "lease":
                lead = lead & (np.asarray(st.hb_armed[:, group]) != 0)
            ok = bool(lead.any())
            out = {"group": group, "slot": slot, "ok": ok,
                   "read_path": self.cfg.read_path,
                   "latency_ticks": READ_L0[self.cfg.read_path]}
            if ok:
                val = int(self._srv["kv_val"][slot, group])
                out["value"] = val
                out["version"] = int(self._srv["kv_ver"][slot, group])
                out["command"] = self.command_name(val)
        return out

    def serving_stats(self) -> dict:
        """§20 serving summary: invariant status, applied/read totals, and the
        submit→commit / read latency percentiles from the carry histograms."""
        self._check_serving()
        from raft_kotlin_tpu.ops.serving import summarize_serving

        with self._lock:
            out = summarize_serving(self._srv)
        # JSON-friendly: the (64,) histograms come back as numpy arrays.
        out["hist_commit"] = [int(v) for v in out["hist_commit"]]
        out["hist_read"] = [int(v) for v in out["hist_read"]]
        return out

    def metrics_snapshot(self) -> dict:
        """One host snapshot in the §21 scrape shape (api/opsplane.
        prometheus_text renders it): tick counter, leader coverage, and —
        on serving configs — the §20 totals/latency percentiles. This is
        the INTERACTIVE twin of continuous_farm's per-segment publish
        dict; absent farm keys (segment, farm_util, ...) simply don't
        render."""
        with self._lock:
            roles = np.asarray(self._state.role)
            ups = np.asarray(self._state.up)
            tick = int(self._state.tick)
            has_srv = self._srv is not None
        lead = ((roles == LEADER) & (ups != 0)).any(axis=0)
        snap = {
            "ticks_total": tick,
            "inv_status": "clean",
            "gauges": {
                "groups": self.cfg.n_groups,
                "nodes_per_group": self.cfg.n_nodes,
                "leader_groups": int(lead.sum()),
                "leaderless_groups": int((~lead).sum()),
            },
        }
        if has_srv:
            s = self.serving_stats()
            snap["inv_status"] = s["status"]
            snap["read_p99"] = s["read_p99"]
            snap["gauges"]["applied_total"] = s["applied_total"]
            snap["gauges"]["reads_ok"] = s["reads_ok"]
            snap["gauges"]["submit_commit_p99"] = s["submit_commit_p99"]
        return snap

    # -- persistence (state arrays + the host-side vocabulary) ---------------

    def save(self, path: str) -> None:
        """Checkpoint state AND vocabulary — entries() of a restored Simulator
        renders identical strings (utils/checkpoint.py carries the extra dict)."""
        from raft_kotlin_tpu.utils import checkpoint

        with self._lock:
            checkpoint.save(path, self._state, self.cfg,
                            extra={"vocab": self._rvocab},
                            serving=self._srv)

    @classmethod
    def restore(cls, path: str) -> "Simulator":
        from raft_kotlin_tpu.utils import checkpoint

        state, cfg, extra = checkpoint.load_with_extra(path)
        sim = cls(cfg, state=state)
        srv = checkpoint.load_serving(path)
        if srv is not None:
            sim._srv = srv
        for word in extra.get("vocab", []):
            sim.intern(word)
        return sim

    def _check_addr(self, group: int, node: int) -> None:
        if not (0 <= group < self.cfg.n_groups):
            raise IndexError(f"group {group} out of range [0, {self.cfg.n_groups})")
        if not (1 <= node <= self.cfg.n_nodes):
            raise IndexError(f"node {node} out of range [1, {self.cfg.n_nodes}]")
