"""Element-op accounting for the tick's phase lattice — the COMPUTE half of
the roofline (VERDICT r04 weak #1: `hbm_bw_frac` 0.168 had no compute-side
anchor, so "memory-bound by design" was half a model).

`phase_body_op_counts(cfg)` traces ops/tick.phase_body exactly as the Pallas
megakernel runs it (flat rank-2 layout, the same BodyFlags the kernel
compiles with, int32 interior) and walks the jaxpr, summing per-primitive
ELEMENT counts:

- `arith_ops`   — elementwise arithmetic/compare/select/convert, counted at
  output element count; reductions counted at INPUT element count (a (C, G)
  sum issues ~C*G lane-ops regardless of its scalar-ish output).
- `move_ops`    — layout/data-movement primitives (broadcast, reshape,
  concat, slice, iota, ...), counted at output element count. These occupy
  issue slots on the VPU path too, but Mosaic folds many of them, so they
  are published separately rather than mixed into the arith figure.

The counts are exact per-trace (no sampling); op count scales linearly in G
(every tensor carries the lane axis), so callers may count at a small G and
scale. `vpu_frac` = arith_ops / (tick_seconds * peak) is a LOWER estimate of
issue-slot occupancy (movement excluded, fusion assumed perfect);
`vpu_frac_upper` includes move_ops. Peak VPU throughput per chip is taken
from the public (8 sublanes x 128 lanes x 4 ALUs x clock) TensorCore VPU
model — see _PEAK_VPU.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from raft_kotlin_tpu.ops import tick as tick_mod
from raft_kotlin_tpu.ops.tick import BodyFlags, make_flags, state_fields
from raft_kotlin_tpu.utils.config import RaftConfig

# Public VPU issue-rate model: 8x128 vector unit, 4 ALUs/cell, chip clock.
# (jax-ml.github.io/scaling-book hardware chapter; clocks are the published
# TensorCore frequencies.) Unknown platforms report None -> frac null.
_PEAK_VPU = {
    "v4": 8 * 128 * 4 * 1.05e9,
    "v5 lite": 8 * 128 * 4 * 0.94e9, "v5e": 8 * 128 * 4 * 0.94e9,
    "v5p": 8 * 128 * 4 * 1.75e9,
    "v6": 8 * 128 * 4 * 0.94e9, "v6e": 8 * 128 * 4 * 0.94e9,
}

_REDUCE = {
    "reduce_sum", "reduce_max", "reduce_min", "reduce_and", "reduce_or",
    "reduce_prod", "argmax", "argmin",
}
_MOVE = {
    "broadcast_in_dim", "reshape", "transpose", "concatenate", "slice",
    "dynamic_slice", "dynamic_update_slice", "iota", "pad", "squeeze",
    "rev", "gather", "scatter", "copy",
}
# Zero-cost bookkeeping primitives.
_FREE = {"stop_gradient", "pjit", "closed_call"}


def peak_vpu_ops_per_sec() -> Optional[float]:
    kind = getattr(jax.devices()[0], "device_kind", "").lower()
    for key, v in _PEAK_VPU.items():
        if key in kind:
            return v
    return None


def _walk(jaxpr, mult, acc):
    for eq in jaxpr.eqns:
        prim = eq.primitive.name
        # Recurse into sub-jaxprs (pjit/scan/cond/while/remat/custom_*).
        sub = []
        length = 1
        if prim == "scan":
            sub = [eq.params["jaxpr"].jaxpr]
            length = eq.params["length"]
        elif prim == "while":
            # Trip count unknown at trace time: count one iteration (the
            # phase lattice itself contains no while loops; this only guards
            # against future callers).
            sub = [eq.params["body_jaxpr"].jaxpr, eq.params["cond_jaxpr"].jaxpr]
        elif prim == "cond":
            sub = [b.jaxpr for b in eq.params["branches"]]
        else:
            for k in ("jaxpr", "call_jaxpr"):
                if k in eq.params:
                    j = eq.params[k]
                    sub = [j.jaxpr if hasattr(j, "jaxpr") else j]
                    break
        if sub:
            for s in sub:
                _walk(s, mult * length, acc)
            continue
        if prim in _FREE:
            continue
        out_elems = max(
            (math.prod(v.aval.shape) for v in eq.outvars), default=0)
        if prim in _REDUCE:
            in_elems = max(
                (math.prod(v.aval.shape) for v in eq.invars
                 if hasattr(v, "aval")), default=0)
            acc["arith"] += mult * in_elems
        elif prim in _MOVE:
            acc["move"] += mult * out_elems
        else:
            acc["arith"] += mult * out_elems


def count_jaxpr_ops(fn, *args) -> dict:
    """{'arith': int, 'move': int} element-op counts of fn(*args)'s jaxpr."""
    jaxpr = jax.make_jaxpr(fn)(*args)
    acc = {"arith": 0, "move": 0}
    _walk(jaxpr.jaxpr, 1, acc)
    return acc


# ---------------------------------------------------------------------------
# Issue-latency roofline (VERDICT r5 next-round #5b): the headline megakernel
# sits at ~17% of BOTH the HBM and VPU ceilings, and the round-5 account was
# "serial dependency chains" with no measured bound. The third roofline is
#   min tick time >= chain_depth x per-op issue latency
# where chain_depth is the longest dependency path through one phase-body
# pass (a jaxpr DAG walk, below) and the per-op latency is MEASURED on the
# live chip by timing a serial op chain whose length is swept
# (measure_op_latency; scripts/probe_issue_latency.py is the standalone
# sweep). bench.py publishes latency_frac = (depth x t_op) / tick_seconds in
# the headline tail: a value near 1 says the tick IS its dependency chain
# and neither bandwidth nor issue-slot counting can explain it further.


def _jaxpr_depth(jaxpr, in_depths):
    """Longest dependency path: list of out-var depths given in-var depths.
    Every non-free primitive adds 1 along its critical path (an estimate —
    real issue latencies differ per op; the measured t_op absorbs the
    average). cond takes the max over branches; while/scan count ONE body
    pass (phase_body contains neither on the headline path — the guard
    mirrors _walk's convention)."""
    env = {}

    def read(v):
        if not hasattr(v, "aval"):  # literal
            return 0
        return env.get(id(v), 0)

    for v, d in zip(jaxpr.invars, in_depths):
        env[id(v)] = d
    for eq in jaxpr.eqns:
        prim = eq.primitive.name
        din = max((read(v) for v in eq.invars), default=0)
        sub = []
        if prim == "cond":
            sub = [b for b in eq.params["branches"]]
        elif prim in ("scan", "while"):
            key = "jaxpr" if prim == "scan" else "body_jaxpr"
            sub = [eq.params[key]]
        else:
            for k in ("jaxpr", "call_jaxpr"):
                if k in eq.params:
                    sub = [eq.params[k]]
                    break
        if sub:
            douts = []
            for s in sub:
                j = s.jaxpr if hasattr(s, "jaxpr") else s
                ins = [read(v) for v in eq.invars][-len(j.invars):] \
                    if len(j.invars) <= len(eq.invars) \
                    else [din] * len(j.invars)
                douts.append(_jaxpr_depth(j, ins))
            dout = [max(ds[i] if i < len(ds) else din for ds in douts)
                    for i in range(len(eq.outvars))]
            for v, d in zip(eq.outvars, dout):
                env[id(v)] = d
            continue
        d = din if prim in _FREE else din + 1
        for v in eq.outvars:
            env[id(v)] = d
    return [read(v) for v in jaxpr.outvars]


def count_jaxpr_depth(fn, *args) -> int:
    """Longest dependency chain (op count) through fn(*args)'s jaxpr."""
    jaxpr = jax.make_jaxpr(fn)(*args)
    outs = _jaxpr_depth(jaxpr.jaxpr, [0] * len(jaxpr.jaxpr.invars))
    return max(outs, default=0)


def phase_body_chain_depth(cfg: RaftConfig, g_count: int = 128,
                           flags: Optional[BodyFlags] = None,
                           by_phase: bool = False):
    """Longest dependency chain of ONE phase_body pass at `cfg` — the op
    count of the serial critical path (independent of G: the lane axis is
    data-parallel). The latency-roofline numerator.

    `by_phase=True` (ISSUE 4 satellite) returns the PER-PHASE attribution
    instead: the lattice is re-traced truncated after each phase boundary
    (phase_body's `cut` — the same ablation scripts/probe_phase_cuts.py
    times on hardware) and the depth DELTAS are reported as
    {"F0", "p1", ..., "p5", "total"} — so a future chain cut can be aimed
    at the deepest phase instead of guessed. Deltas can be 0 (a phase whose
    chains fit under an earlier phase's depth adds nothing to the critical
    path)."""
    if not by_phase:
        _, s_in, a_in, f = _phase_body_shapes(cfg, g_count, flags)
        return count_jaxpr_depth(f, s_in, a_in)
    depths = []
    for c in (0, 1, 2, 3, 4, 99):
        _, s_in, a_in, f = _phase_body_shapes(cfg, g_count, flags, cut=c)
        depths.append(count_jaxpr_depth(f, s_in, a_in))
    keys = ("F0", "p1", "p2", "p3", "p4", "p5")
    out = {k: depths[i] - (depths[i - 1] if i else 0)
           for i, k in enumerate(keys)}
    out["total"] = depths[-1]
    return out


def time_op_chain(k: int, reps: int = 5) -> float:
    """Min wall time (seconds) of a jitted serial chain of k dependent
    xorshift rounds (2 elementwise ops per round — non-affine, so XLA
    cannot algebraically collapse it) on one (8, 128) vreg-sized int32
    block. The ONE chain/timing definition shared by measure_op_latency
    (2-point slope, bench.py inline) and scripts/probe_issue_latency.py
    (least-squares sweep) — so both publish the same t_op roofline."""
    import time

    x0 = jnp.arange(8 * 128, dtype=jnp.int32).reshape(8, 128)

    @jax.jit
    def f(x):
        for _ in range(k):
            x = x ^ (x << 1)  # 2 dependent ops per round
        return x

    jax.block_until_ready(f(x0))  # compile + warm
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(f(x0))
        ts.append(time.perf_counter() - t0)
    return min(ts)


def measure_op_latency(chain: int = 2048, reps: int = 5):
    """Measured per-op issue latency (seconds) on the CURRENT backend: time
    the op chain at two lengths and take the slope (differencing removes
    dispatch/launch overhead). Returns None if the measurement is
    degenerate (e.g. a backend that folds the chain)."""
    t1, t2 = time_op_chain(chain, reps), time_op_chain(2 * chain, reps)
    slope = (t2 - t1) / chain  # Δrounds = chain -> seconds per round (2 ops)
    if slope <= 0:
        return None
    return slope / 2


def _phase_body_shapes(cfg, g_count, flags, cut=None):
    """Shared input-shape construction for the op-count and chain-depth
    walks (one copy of the field/aux shape tables). `cut` truncates the
    traced lattice after that phase (phase_body's explicit-cut path — no
    env var, no warning; analysis only)."""
    from raft_kotlin_tpu.ops.pallas_tick import kernel_field_dtype

    N, C = cfg.n_nodes, cfg.phys_capacity
    if flags is None:
        flags = make_flags(cfg)
    sfields = state_fields(flags)
    g = g_count
    field_shapes = {
        **{k: (N, g) for k in sfields},
        "log_term": (N * C, g), "log_cmd": (N * C, g),
        "responded": (N * N, g), "next_index": (N * N, g),
        "match_index": (N * N, g), "link_up": (N * N, g),
        **{k: (N * N, g) for k in tick_mod.MAILBOX_FIELDS},
    }
    aux_shapes = {
        "edge_iid": (N * N, g), "crash_m": (N, g), "restart_m": (N, g),
        "link_fail": (N * N, g), "link_heal": (N * N, g),
        "el_draw_f": (N, g), "bdraw": (N, g), "periodic": (1, g),
        "inject": (N, g), "delay": (N * N, g),
    }
    aux_names = tuple(
        k for k in tick_mod.AUX_FIELDS
        if (k in ("edge_iid", "bdraw"))
        or (k in ("crash_m", "restart_m", "el_draw_f") and flags.faults)
        or (k in ("link_fail", "link_heal") and flags.links)
        or (k == "periodic" and flags.periodic)
        or (k == "inject" and flags.inject)
        or (k == "delay" and flags.delay and cfg.delay_lo < cfg.delay_hi)
    )
    bool_state = ("el_armed", "hb_armed", "up")

    def fld(k):
        if k in bool_state:
            return jnp.bool_
        return kernel_field_dtype(cfg, k)

    s_in = [jax.ShapeDtypeStruct(field_shapes[k], fld(k)) for k in sfields]
    a_in = [jax.ShapeDtypeStruct(aux_shapes[k],
                                 jnp.bool_ if k in ("crash_m", "restart_m")
                                 else jnp.int32)
            for k in aux_names]

    def f(svals, avals):
        s = dict(zip(sfields, svals))
        aux = dict(zip(aux_names, avals))
        el = tick_mod.phase_body(cfg, s, aux, flags, cut=cut)
        return tuple(s[k] for k in sfields) + (el,)

    return flags, s_in, a_in, f


def phase_body_op_counts(cfg: RaftConfig, g_count: int = 256,
                         flags: Optional[BodyFlags] = None) -> dict:
    """Element-op counts of ONE phase_body pass at `cfg`, counted at
    g_count lanes and scaled to cfg.n_groups (exact: every tensor in the
    lattice carries the lane axis). Uses the Pallas kernel's interior
    layout (rank-2, int32 interior, storage-dtype logs) so the count
    anchors the megakernel's compute side."""
    _, s_in, a_in, f = _phase_body_shapes(cfg, g_count, flags)
    acc = count_jaxpr_ops(f, s_in, a_in)
    scale = cfg.n_groups / g_count
    return {"arith": int(acc["arith"] * scale),
            "move": int(acc["move"] * scale)}
