"""§20 serving path: the applied KV state machine, log-free reads and
client-latency histograms (SEMANTICS.md §20 — ISSUE 19).

The reference's entire client surface (RaftServer.kt's HTTP POST/GET) sits
ON TOP of consensus: commands enter the log, and once committed they are
APPLIED to a state machine whose contents clients read back. This module is
that layer, vectorized groups-minor like everything else:

* **Applied KV store** — a fixed-slot `(S, G)` int32 value plane plus an
  `(S, G)` write-version plane per group (`cfg.serve_slots` = S). An
  end-of-tick apply phase folds the committed prefix forward at most
  `cfg.apply_chunk` entries per tick: entry at logical position p lands in
  slot `cmd % S`, and the running `apply_digest` advances by the SAME
  wrapping-int32 fold as the §15 snapshot digest (`fold_digest`,
  DIGEST_MULT) — so a node's snap_digest IS the apply digest of its folded
  prefix, and r15 snapshots/InstallSnapshot ship real applied state. When
  the apply cursor falls behind the source node's snapshot base (§15), the
  cursor fast-forwards by installing snap_digest directly (the
  InstallSnapshot rule on the state machine); skipped entries are counted
  in `snap_jumps`.

* **Latency histograms in the carry** — the periodic/injected workloads
  store THE SUBMIT TICK as the command value (ops/tick phase 0 /
  cfg.cmd_period), so submit→apply latency is exactly
  `apply_tick - cmd_value`: binned into a carry-resident (64,) int32
  histogram (`hist_commit`) with bin 63 absorbing overflow — the §19
  TIMING_KEYS transport contract (static shapes, order-independent integer
  sums, one readback; a sharded run's summed histogram is bit-equal to
  single-device). `hist_read` bins read latency under the same contract.

* **Log-free reads (§6.4/§8, Ongaro & Ousterhout 2014)** — a read never
  touches the log; it needs only a leadership-confirmation round:
  `read_path="readindex"` serves when the group has a live leader, at a
  2-tick confirmation latency (commit-frontier confirmation via a
  heartbeat round); `read_path="lease"` serves when a live leader holds an
  armed heartbeat lease, at 1 tick. Blocked ticks queue the batch
  (`grp_read_q`) and age it (`grp_read_age`); when leadership returns the
  whole queue serves at `L0 + age-of-oldest` (the conservative aggregate
  rule — ONE bin per flush, exactly recomputable from a (T, N, G)
  role/up trace). Served reads fold one drawn key's current value into
  `read_digest` per group per tick — the §17 kernel-twin threefry draws
  (KIND_READ channel, hot-slot skew from the scenario bank's client_hot
  row) keyed so the device evaluation and the host recomputation
  (`fold_from_trace`) produce identical bits.

* **Device-resident load generation** — `gen_inject` derives a (G, N)
  phase-0 inject plane from the base key's §17 twin words at
  (KIND_CLIENT, tick): per group, `client_rate` writers (scenario-bank
  row; default 1) each target a uniformly drawn node with command value =
  the tick. Generation happens INSIDE the scan body (zero HBM aux
  traffic); `host_stream` evaluates the identical function eagerly on the
  host, and `make_queued_run` feeds such a precomputed stream through a
  double-buffered chunked scan — the device-generator ≡ host-queue
  bit-equality theorem (tests/test_serving.py).

The serving carry (`srv`) is a sibling of the §11 monitor carry: a dict of
fixed-shape int32 arrays threaded through every engine's scan, advanced by
`serving_step` on the POST-tick state view, bit-neutral to protocol state.
It runs in plain XLA in every engine (the fused Pallas path replays its
staged per-tick snapshots, exactly like the monitor) — the Mosaic-interior
embedding is a routed-but-unpinned follow-up (the `read_path` plan
dimension; scripts/probe_serving.py --pin).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_kotlin_tpu.constants import LEADER as _LEADER
from raft_kotlin_tpu.models.state import DIGEST_MULT
from raft_kotlin_tpu.utils import rng as rngmod
from raft_kotlin_tpu.utils.config import RaftConfig

_I32 = jnp.int32

# Histogram bin count — same transport contract as telemetry.TIMING_BINS
# (width-1 tick bins, bin SERVING_BINS-1 absorbs overflow).
SERVING_BINS = 64

# Leadership-confirmation latency of each read path, in ticks: read-index
# needs a commit-frontier confirmation round (heartbeat out + ack back),
# lease reads serve locally under an armed heartbeat lease.
READ_L0 = {"readindex": 2, "lease": 1}

# The canonical carry keys (checkpoint v9 iterates this order; shapes for
# G groups, S slots, B = SERVING_BINS — all int32).
SERVING_KEYS = (
    "tick",            # ()    post-tick count (== state.tick after the step)
    "kv_val",          # (S,G) applied value plane (slot = cmd % S)
    "kv_ver",          # (S,G) per-slot write count (0 = never written)
    "applied",         # (G,)  apply cursor: logical prefix length applied
    "apply_digest",    # (G,)  §15 fold of every applied cmd (DIGEST_MULT)
    "read_digest",     # (G,)  fold of served drawn-key values
    "applied_total",   # ()    total entries applied across groups
    "snap_jumps",      # ()    entries skipped by InstallSnapshot fast-fwd
    "reads_ok",        # ()    total reads served
    "grp_read_q",      # (G,)  queued (blocked) read count
    "grp_read_age",    # (G,)  ticks the oldest queued batch has waited
    "hist_commit",     # (B,)  submit→apply latency histogram
    "hist_read",       # (B,)  read-service latency histogram
    "serve_viol",      # (G,)  sticky latch: commit frontier < apply cursor
    "viol_tick",       # ()    first-violation tick (-1 = clean)
)


# The carry keys scoped to ONE lane (universe) — last axis (G,), reset
# when the §19 continuous farm folds a retired lane back to init
# (api/fuzz.make_continuous_runner). Named explicitly, never by shape:
# the (B,) histograms and a (G,) lane row can share an extent, and the
# histograms/totals are farm-global accumulators that must survive lane
# turnover.
SERVING_LANE_KEYS = ("kv_val", "kv_ver", "applied", "apply_digest",
                     "read_digest", "grp_read_q", "grp_read_age",
                     "serve_viol")


def serving_enabled(cfg: RaftConfig) -> bool:
    """Whether `cfg` compiles the serving path in (S > 0). S == 0 configs
    compile it OUT entirely — the migration-equality contract."""
    return getattr(cfg, "serve_slots", 0) > 0


def serving_zeros(n_groups: int, slots: int,
                  bins: int = SERVING_BINS) -> Dict[str, jax.Array]:
    """A fresh serving carry (see SERVING_KEYS for shapes/semantics)."""
    G, S = int(n_groups), int(slots)
    return {
        "tick": jnp.zeros((), _I32),
        "kv_val": jnp.zeros((S, G), _I32),
        "kv_ver": jnp.zeros((S, G), _I32),
        "applied": jnp.zeros((G,), _I32),
        "apply_digest": jnp.zeros((G,), _I32),
        "read_digest": jnp.zeros((G,), _I32),
        "applied_total": jnp.zeros((), _I32),
        "snap_jumps": jnp.zeros((), _I32),
        "reads_ok": jnp.zeros((), _I32),
        "grp_read_q": jnp.zeros((G,), _I32),
        "grp_read_age": jnp.zeros((G,), _I32),
        "hist_commit": jnp.zeros((bins,), _I32),
        "hist_read": jnp.zeros((bins,), _I32),
        "serve_viol": jnp.zeros((G,), _I32),
        "viol_tick": jnp.full((), -1, _I32),
    }


def serving_init(cfg: RaftConfig, enabled: bool = True
                 ) -> Optional[Dict[str, jax.Array]]:
    """THE runner-side serving-carry constructor (the monitor_init twin):
    a fresh carry, or None when serving is off for this config/runner."""
    if not enabled or not serving_enabled(cfg):
        return None
    return serving_zeros(cfg.n_groups, cfg.serve_slots)


# The state fields serving_step reads — a SUBSET of the monitor's staged
# fused-snapshot set (MONITOR_STATE_FIELDS / MONITOR_COMPACT_FIELDS), so
# fused launches that snapshot for serving reuse the monitor's transport.
SERVING_STATE_FIELDS = ("role", "up", "commit", "hb_armed", "log_cmd")
SERVING_COMPACT_FIELDS = ("snap_index", "snap_digest")


def serving_flat_view(flat: dict, n_nodes: int) -> dict:
    """The serving view of the flat rank-2 kernel layout (log_cmd
    (N*C, G) -> (N, C, G)) — the Pallas flat-carry runner's form."""
    N = n_nodes
    v = {k: flat[k] for k in SERVING_STATE_FIELDS if k != "log_cmd"}
    a = flat["log_cmd"]
    v["log_cmd"] = a.reshape(N, -1, a.shape[-1])
    for k in SERVING_COMPACT_FIELDS:
        v[k] = flat.get(k)
    return v


def serving_view(state) -> dict:
    """The serving view of a RaftState: exactly the fields serving_step
    reads, all present in the monitor's staged fused-snapshot set too
    (MONITOR_STATE_FIELDS + MONITOR_COMPACT_FIELDS), so every engine can
    feed the step from views it already materializes."""
    v = {k: getattr(state, k) for k in
         ("role", "up", "commit", "hb_armed", "log_cmd")}
    for k in ("snap_index", "snap_digest"):
        v[k] = getattr(state, k, None)
    return v


def _bump(hist: jax.Array, slot: jax.Array, count: jax.Array) -> jax.Array:
    """hist[slot_g] += count_g for each group g — the §19 one-hot bump
    (order-independent int sums; slot already clipped)."""
    B = hist.shape[0]
    hits = (lax.iota(_I32, B)[:, None] == slot[None, :]).astype(_I32)
    return hist + jnp.sum(hits * count[None, :], axis=1)


def serving_step(cfg: RaftConfig, view: dict, srv: Dict[str, jax.Array],
                 kw=None, scen: Optional[dict] = None
                 ) -> Dict[str, jax.Array]:
    """One serving step on the POST-tick state `view` (serving_view /
    monitor_view / the fused snapshot replay form — log_cmd (N, C, G)).
    Returns the advanced carry (a new dict; inputs untouched).

    `kw` is the base key's §17 twin words (k0, k1) from rng.kt_key_words —
    needed only for the read-digest draws; None skips the drawn-key fold
    (read gating/latency still run). `scen` is the scenario bank (client_*
    rows ride it when the spec carries them)."""
    S, A = cfg.serve_slots, cfg.apply_chunk
    C = cfg.phys_capacity
    B = srv["hist_commit"].shape[0]
    G = srv["applied"].shape[0]
    t = srv["tick"]
    out = dict(srv)

    # -- apply phase: fold the committed prefix into the KV planes --------
    cm = view["commit"].astype(_I32)                     # (N, G)
    F = jnp.max(cm, axis=0)                              # group frontier
    src = jnp.argmax(cm, axis=0)                         # its holder
    # A node's own commit never exceeds its own matched prefix, so src's
    # log contains every entry the cursor will read this tick; committed
    # prefixes agree across holders (Log Matching), so holder choice is
    # value-neutral.
    lc_src = jnp.take_along_axis(
        view["log_cmd"].astype(_I32), src[None, None, :], axis=0)[0]  # (C,G)
    applied = srv["applied"]
    dg = srv["apply_digest"]
    kv_val, kv_ver = srv["kv_val"], srv["kv_ver"]

    # Safety latch: a frontier BELOW the cursor means a committed entry
    # vanished — never legal; sticky, with a first-violation tick.
    bad = F < applied
    out["serve_viol"] = srv["serve_viol"] | bad.astype(_I32)
    newly = (srv["viol_tick"] < 0) & jnp.any(bad)
    out["viol_tick"] = jnp.where(newly, t, srv["viol_tick"])

    # §15 InstallSnapshot on the state machine: if src has folded past the
    # cursor, the skipped entries exist only as src's snap_digest — and the
    # apply fold IS the snapshot fold, so installing it fast-forwards the
    # cursor exactly. Per-key granularity of the skipped span is lost
    # (counted in snap_jumps), matching a real snapshot install.
    si = view.get("snap_index")
    if si is not None:
        base = jnp.take_along_axis(si.astype(_I32), src[None, :], axis=0)[0]
        sdg = jnp.take_along_axis(
            view["snap_digest"].astype(_I32), src[None, :], axis=0)[0]
        jump = base > applied
        dg = jnp.where(jump, sdg, dg)
        out["snap_jumps"] = srv["snap_jumps"] + jnp.sum(
            jnp.where(jump, base - applied, 0))
        applied = jnp.maximum(applied, base)

    want = jnp.clip(F - applied, 0, A)                   # (G,)
    slot_iota = lax.broadcasted_iota(_I32, (S, G), 0)
    hist_c = srv["hist_commit"]
    for j in range(A):
        active = jnp.asarray(j, _I32) < want             # (G,) bool
        # Physical row of logical position p: p % C (ring base = the §15
        # snapshot index; identity for static logs, where p < C always).
        row = jnp.remainder(applied + j, C)
        cv = jnp.take_along_axis(lc_src, row[None, :], axis=0)[0]
        dg = jnp.where(active, dg * jnp.asarray(DIGEST_MULT, _I32) + cv, dg)
        hot = (slot_iota == jnp.remainder(cv, S)[None, :]) & active[None, :]
        kv_val = jnp.where(hot, cv[None, :], kv_val)
        kv_ver = kv_ver + hot.astype(_I32)
        # Tick-valued workloads (cmd_period / gen_inject / the Simulator's
        # tick-stamped POSTs) make t - cv the exact submit→apply latency;
        # foreign values just clip into the edge bins.
        lat = jnp.clip(t - cv, 0, B - 1)
        hist_c = _bump(hist_c, lat, active.astype(_I32))
    out["applied"] = applied + want
    out["apply_digest"] = dg
    out["kv_val"], out["kv_ver"] = kv_val, kv_ver
    out["applied_total"] = srv["applied_total"] + jnp.sum(want)
    out["hist_commit"] = hist_c

    # -- read phase: log-free reads under leadership confirmation --------
    if scen is not None and "client_read" in scen:
        R = scen["client_read"].astype(_I32)             # (G,) batch size
    else:
        R = jnp.full((G,), cfg.read_batch, _I32)
    lease = cfg.read_path == "lease"
    L0 = READ_L0[cfg.read_path]
    lead = (view["role"].astype(_I32) == _LEADER) & (view["up"] != 0)
    if lease:
        ok = jnp.any(lead & (view["hb_armed"] != 0), axis=0)
    else:
        ok = jnp.any(lead, axis=0)
    q, age = srv["grp_read_q"], srv["grp_read_age"]
    hist_r = srv["hist_read"]
    served_now = jnp.where(ok, R, 0)
    # Fresh batch at the protocol floor L0; the flushed queue at
    # L0 + age-of-oldest (the conservative aggregate rule — see module
    # docstring; exactly recomputable from a role/up trace).
    hist_r = _bump(hist_r, jnp.full((G,), min(L0, B - 1), _I32), served_now)
    flushed = jnp.where(ok, q, 0)
    hist_r = _bump(hist_r, jnp.clip(L0 + age, 0, B - 1), flushed)
    out["reads_ok"] = srv["reads_ok"] + jnp.sum(served_now) \
        + jnp.sum(flushed)
    out["grp_read_q"] = jnp.where(ok, 0, q + R)
    out["grp_read_age"] = jnp.where(
        ok, 0, jnp.where(q > 0, age + 1, jnp.where(R > 0, 1, 0)))
    out["hist_read"] = hist_r

    # Served drawn-key fold: one key per group per served tick, drawn on
    # the §17 twin lattice at (KIND_READ, t) — hot-slot skew from the
    # bank's client_hot permille row (threshold arithmetic exact in i32:
    # hot * 2^23 // 1000 == hot * 8388 + hot * 608 // 1000).
    if kw is not None:
        k0, k1 = kw
        e0, e1 = rngmod.kt_event_key(k0, k1, rngmod.KIND_READ, t)
        h0, h1 = rngmod.kt_fold(e0, e1, 0)
        s0, s1 = rngmod.kt_fold(e0, e1, 1)
        gidx = lax.iota(_I32, G)
        if scen is not None and "client_hot" in scen:
            hotp = scen["client_hot"].astype(_I32)
            thresh = hotp * jnp.asarray(8388, _I32) \
                + (hotp * jnp.asarray(608, _I32)) // 1000
            hotm = rngmod.kt_bits23(h0, h1, gidx) < thresh
        else:
            hotm = jnp.zeros((G,), bool)
        slot_r = jnp.where(
            hotm, 0, rngmod.kt_randint(s0, s1, gidx, 0, jnp.asarray(S, _I32)))
        val_r = jnp.take_along_axis(kv_val, slot_r[None, :], axis=0)[0]
        fold = ok & (R > 0)
        out["read_digest"] = jnp.where(
            fold, srv["read_digest"] * jnp.asarray(DIGEST_MULT, _I32) + val_r,
            srv["read_digest"])

    out["tick"] = t + 1
    return out


# ---------------------------------------------------------------------------
# Device-resident load generation (§20) + the host-queue twin.


def gen_inject(cfg: RaftConfig, k0, k1, t, scen: Optional[dict] = None
               ) -> jax.Array:
    """The (G, N) phase-0 inject plane for tick `t`, derived entirely from
    the base key's §17 twin words — per group, `client_rate` writers (bank
    row; default 1/tick) each target a uniformly drawn node, command value
    = t (the submit-tick identity the latency histograms rely on).
    Evaluates identically inside a scan body (device generator) and
    eagerly on the host (host_stream) — the bit-equality contract."""
    N, G = cfg.n_nodes, cfg.n_groups
    spec = cfg.scenario
    w_max = min(N, max(1, spec.client_rate_max if spec is not None else 1))
    if scen is not None and "client_rate" in scen:
        rate = jnp.minimum(scen["client_rate"].astype(_I32), N)
    else:
        rate = jnp.ones((G,), _I32)
    t = jnp.asarray(t, _I32)
    e0, e1 = rngmod.kt_event_key(k0, k1, rngmod.KIND_CLIENT, t)
    n0, n1 = rngmod.kt_fold(e0, e1, 2)
    gidx = lax.iota(_I32, G)
    inj = jnp.full((G, N), -1, _I32)
    for j in range(w_max):
        nd = rngmod.kt_randint(n0, n1, gidx * w_max + j, 0,
                               jnp.asarray(N, _I32))          # (G,)
        m = jnp.asarray(j, _I32) < rate
        oh = lax.iota(_I32, N)[None, :] == nd[:, None]        # (G, N)
        inj = jnp.where(oh & m[:, None], t, inj)
    return inj


def host_stream(cfg: RaftConfig, n_ticks: int, t0: int = 0,
                scen: Optional[dict] = None) -> np.ndarray:
    """The host-side twin of the device generator: the (T, G, N) inject
    stream for ticks [t0, t0 + n_ticks), evaluated eagerly through the
    SAME gen_inject — what make_queued_run's host fill loop produces."""
    base = rngmod.base_key(cfg.seed)
    k0, k1 = rngmod.kt_key_words(base)
    rows = [gen_inject(cfg, k0, k1, t0 + i, scen=scen)
            for i in range(n_ticks)]
    return np.asarray(jax.device_get(jnp.stack(rows)))


def make_queued_run(cfg: RaftConfig, n_ticks: int, chunk: int = 16):
    """The host-fed ingestion path: a jitted chunked scan whose xs is a
    (chunk, G, N) inject buffer, double-buffered on the host — while the
    device drains chunk k (async dispatch), the host fills buffer k+1.
    Returns run(state, fill_fn) -> (end_state, srv, stats); fill_fn(t0, n)
    must return the (n, G, N) int32 inject stream for ticks [t0, t0+n)
    (serving.host_stream partial-applied, or any external workload).
    stats reports the fill/compute overlap: fill_hidden_frac is the
    fraction of host fill time hidden behind device execution."""
    import time

    from raft_kotlin_tpu.ops.tick import make_rng, make_tick, split_rng

    if not serving_enabled(cfg):
        raise ValueError("make_queued_run needs cfg.serve_slots > 0")
    tick_fn = make_tick(cfg)
    rng = make_rng(cfg)
    n_chunks, rem = divmod(int(n_ticks), int(chunk))
    sizes = [chunk] * n_chunks + ([rem] if rem else [])

    @jax.jit
    def run_chunk(st, srv, rng, xs):
        base, _tk, _bk, scen = split_rng(rng)
        kw = rngmod.kt_key_words(base)

        def body(carry, inj):
            st, srv = carry
            st2 = tick_fn(st, inject=inj, rng=rng)
            srv2 = serving_step(cfg, serving_view(st2), srv, kw=kw,
                                scen=scen)
            return (st2, srv2), None

        (st, srv), _ = lax.scan(body, (st, srv), xs)
        return st, srv

    def run(state, fill_fn):
        srv = serving_zeros(cfg.n_groups, cfg.serve_slots)
        t0 = 0
        fill_s = hidden_s = 0.0
        nxt = fill_fn(0, sizes[0]) if sizes else None
        for i, n in enumerate(sizes):
            buf, nxt = nxt, None
            state, srv = run_chunk(state, srv, rng,
                                   jnp.asarray(buf, _I32))
            # Device is (asynchronously) draining chunk i: fill i+1 NOW,
            # then block on the in-flight result — fill time that fits
            # under the device time is hidden.
            if i + 1 < len(sizes):
                f0 = time.perf_counter()
                nxt = fill_fn(t0 + n, sizes[i + 1])
                f1 = time.perf_counter()
                fill_s += f1 - f0
                jax.block_until_ready(state.term)
                # If the device was still draining chunk i when the fill
                # finished (we then blocked a measurable time), the whole
                # fill ran under device execution — hidden.
                if time.perf_counter() - f1 > 1e-5:
                    hidden_s += f1 - f0
            t0 += n
        jax.block_until_ready(state.term)
        stats = {"fill_s": fill_s,
                 "fill_hidden_frac": (hidden_s / fill_s) if fill_s else 1.0}
        return state, srv, stats

    return run


# ---------------------------------------------------------------------------
# Host recomputation + scalar/summary forms.


def fold_from_trace(cfg: RaftConfig, commit_tr: np.ndarray,
                    end_log_cmd: np.ndarray,
                    role_tr: Optional[np.ndarray] = None,
                    up_tr: Optional[np.ndarray] = None,
                    scen: Optional[dict] = None) -> dict:
    """Exact host recomputation of the serving carry from a (T, N, G)
    trace — the §19 recomputability contract. `commit_tr` is the per-tick
    post-tick commit trace, `end_log_cmd` the END state's (N, C, G)
    log_cmd (committed prefixes are never truncated, so the end log of
    each tick's frontier holder contains every applied value; requires a
    no-compaction config, where positions are stable rows). `role_tr`/
    `up_tr` add the read-index read channel (role/up ride every
    make_run trace; the lease path needs hb_armed and is pinned
    differentially instead). Returns numpy arrays keyed like the carry.

    The read-digest fold additionally needs the §17 twin draws — evaluated
    here eagerly via the same kt_* functions the device used."""
    if cfg.uses_compaction:
        raise ValueError("fold_from_trace needs stable log rows "
                         "(no-compaction config)")
    T, N, G = commit_tr.shape
    S, A, C = cfg.serve_slots, cfg.apply_chunk, cfg.phys_capacity
    B = SERVING_BINS
    cm = np.asarray(commit_tr, np.int64)
    lc = np.asarray(end_log_cmd, np.int64)
    applied = np.zeros(G, np.int64)
    dg = np.zeros(G, np.int64)
    kv_val = np.zeros((S, G), np.int64)
    kv_ver = np.zeros((S, G), np.int64)
    hist_c = np.zeros(B, np.int64)
    hist_r = np.zeros(B, np.int64)
    reads_ok = 0
    rdg = np.zeros(G, np.int64)
    q = np.zeros(G, np.int64)
    age = np.zeros(G, np.int64)
    applied_total = 0

    do_reads = role_tr is not None and up_tr is not None
    if scen is not None and "client_read" in scen:
        R = np.asarray(jax.device_get(scen["client_read"]), np.int64)
    else:
        R = np.full(G, cfg.read_batch, np.int64)
    L0 = READ_L0[cfg.read_path]
    if do_reads and cfg.read_path != "readindex":
        raise ValueError("trace recompute covers read_path='readindex' "
                        "(lease needs hb_armed, absent from run traces)")
    base = rngmod.base_key(cfg.seed)
    k0, k1 = (int(x) for x in jax.device_get(rngmod.kt_key_words(base)))

    for t in range(T):
        F = cm[t].max(axis=0)
        src = cm[t].argmax(axis=0)
        want = np.clip(F - applied, 0, A)
        for g in range(G):
            for j in range(int(want[g])):
                p = int(applied[g]) + j
                cv = int(lc[src[g], p % C, g])
                dg[g] = (dg[g] * DIGEST_MULT + cv) & 0xFFFFFFFF
                kv_val[cv % S, g] = cv
                kv_ver[cv % S, g] += 1
                hist_c[min(max(t - cv, 0), B - 1)] += 1
        applied_total += int(want.sum())
        applied = applied + want
        if do_reads:
            lead = (np.asarray(role_tr[t], np.int64) == 2) \
                & (np.asarray(up_tr[t], np.int64) != 0)
            ok = lead.any(axis=0)
            served_now = np.where(ok, R, 0)
            hist_r[min(L0, B - 1)] += int(served_now.sum())
            for g in range(G):
                if ok[g] and q[g] > 0:
                    hist_r[min(L0 + int(age[g]), B - 1)] += int(q[g])
            reads_ok += int(served_now.sum()) \
                + int(np.where(ok, q, 0).sum())
            # Drawn-key fold (device-identical bits via the kt twins).
            e0, e1 = rngmod.kt_event_key(np.int32(k0), np.int32(k1),
                                         rngmod.KIND_READ, np.int32(t))
            h0, h1 = rngmod.kt_fold(e0, e1, 0)
            s0, s1 = rngmod.kt_fold(e0, e1, 1)
            gidx = np.arange(G, dtype=np.int32)
            if scen is not None and "client_hot" in scen:
                hotp = np.asarray(jax.device_get(scen["client_hot"]),
                                  np.int64)
                thresh = hotp * 8388 + (hotp * 608) // 1000
                hotm = np.asarray(jax.device_get(
                    rngmod.kt_bits23(jnp.asarray(h0), jnp.asarray(h1),
                                     jnp.asarray(gidx)))) < thresh
            else:
                hotm = np.zeros(G, bool)
            slot_r = np.asarray(jax.device_get(rngmod.kt_randint(
                jnp.asarray(s0), jnp.asarray(s1), jnp.asarray(gidx),
                0, jnp.asarray(S, jnp.int32))), np.int64)
            slot_r = np.where(hotm, 0, slot_r)
            for g in range(G):
                if ok[g] and R[g] > 0:
                    rdg[g] = (rdg[g] * DIGEST_MULT
                              + int(kv_val[slot_r[g], g])) & 0xFFFFFFFF
            q = np.where(ok, 0, q + R)
            age = np.where(ok, 0, np.where(q > 0, age + 1,
                                           np.where(R > 0, 1, 0)))

    def sign32(a):
        a = np.asarray(a, np.int64) & 0xFFFFFFFF
        return (a - ((a >= (1 << 31)) * (1 << 32))).astype(np.int64)

    return {
        "applied": applied, "apply_digest": sign32(dg),
        "read_digest": sign32(rdg), "kv_val": kv_val, "kv_ver": kv_ver,
        "applied_total": applied_total, "reads_ok": reads_ok,
        "hist_commit": hist_c, "hist_read": hist_r,
    }


def hist_percentile(hist, p: float) -> int:
    """The p-quantile BIN (in ticks) of a (B,) count histogram: the first
    bin whose cumulative count reaches p * total (total 0 -> 0)."""
    h = np.asarray(jax.device_get(hist), np.int64)
    total = int(h.sum())
    if total == 0:
        return 0
    cum = np.cumsum(h)
    return int(np.searchsorted(cum, p * total, side="left"))


def serving_scalars(srv: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
    """The carry as FLAT () int32 scalars under the srv_ prefix — the form
    that rides bench.measure's stats dicts (the monitor_scalars twin)."""
    return {
        "srv_applied_total": srv["applied_total"],
        "srv_reads_ok": srv["reads_ok"],
        "srv_snap_jumps": srv["snap_jumps"],
        "srv_viol_groups": jnp.sum((srv["serve_viol"] != 0).astype(_I32)),
        "srv_viol_tick": srv["viol_tick"],
        "srv_hist_commit_n": jnp.sum(srv["hist_commit"]),
        "srv_hist_read_n": jnp.sum(srv["hist_read"]),
    }


def serving_status(stats: Optional[dict]) -> Optional[str]:
    """The compact serving_inv_status string from serving_scalars output
    (host ints): "clean", or "applied-ahead@t<tick>" when the frontier
    ever regressed below the apply cursor. None when the leg ran
    serving-off."""
    if not stats or "srv_viol_tick" not in stats:
        return None
    t = int(stats["srv_viol_tick"])
    if t < 0:
        return "clean"
    return f"applied-ahead@t{t}"


def summarize_serving(srv: Dict[str, jax.Array]) -> dict:
    """Host materialization of a serving carry — ONE batched device_get:
    totals, the violation latch, and p50/p99/p999 of both histograms."""
    host = jax.device_get(srv)
    stats = {k: int(np.asarray(host[k])) if np.asarray(host[k]).ndim == 0
             else np.asarray(host[k]) for k in host}
    hc, hr = stats["hist_commit"], stats["hist_read"]
    return {
        "status": serving_status(
            {"srv_viol_tick": stats["viol_tick"]}),
        "applied_total": stats["applied_total"],
        "reads_ok": stats["reads_ok"],
        "snap_jumps": stats["snap_jumps"],
        "submit_commit_p50": hist_percentile(hc, 0.50),
        "submit_commit_p99": hist_percentile(hc, 0.99),
        "submit_commit_p999": hist_percentile(hc, 0.999),
        "read_p50": hist_percentile(hr, 0.50),
        "read_p99": hist_percentile(hr, 0.99),
        "read_p999": hist_percentile(hr, 0.999),
        "hist_commit": hc,
        "hist_read": hr,
    }
