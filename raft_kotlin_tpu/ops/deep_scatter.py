"""Pallas one-hot log-write kernel — the deep engine's scatter alternative.

The batched deep engine ends each tick with 2 XLA scatters (term + cmd)
applying ~K resolved rows per node (ops/tick.py deferred writes). The
round-5 probe model: an XLA scatter's cost scales with OPERAND SIZE (it
materializes a copy unless the while-body donates in place), and even the
donated in-context form pays tens of ms at config-5 scale. This module
applies BOTH arrays' writes in ONE pass over the logs, as a K-deep one-hot
select chain over (Cb, tile) slabs: `iota + chunk_offset == row` — compare
shared by term and cmd (the two arrays write the same rows by
construction). K is SMALL (~N+1 per node), so the VPU cost (K * C * G
compares/selects) stays a few ms — the regime where one-hot beats
gather/scatter lowering. (READS are the opposite: R~36 rows/node makes a
one-hot read stream VPU-bound, which is why the read side uses XLA takes —
ops/deep_gather.py docstring.) Rows are LOCAL slot indices in [0, C);
row == C means "dropped" (masked write) and matches no slab row.

Two kernel forms (round 6; ROUND5.md priced the grid form at ~22 ms/tick
against a 9 ms whole-log DMA floor — 2.5x, the last identified write
lever):

1. **DMA form (default)** — grid (N, G//tile) with the C-chunk loop INSIDE
   the kernel as manual double-buffered `pltpu.make_async_copy` slabs over
   logs kept in HBM (`memory_space=ANY`, input-output aliased):
   - a slab only crosses HBM AT ALL if some lane of the tile writes into
     it (a per-chunk any-hit test on the (K, tile) row block, which is
     already VMEM-resident). The deferred writes cluster at the per-pair
     frontier rows, so most (node, tile) steps touch a handful of chunks —
     the whole-log round-trip "floor" of the grid form was never a floor
     of the PROBLEM, only of its grid formulation;
   - touched slabs are pipelined through 2 VMEM slots: chunk c+1's read
     DMA is issued before chunk c's compute, and chunk c's write-back DMA
     overlaps chunk c+1's compute — the explicit overlap the grid form's
     aliased in/out blocks did not get from the automatic pipeliner;
   - untouched slabs are preserved by the input/output aliasing (the
     caller's donated buffer IS the output; XLA inserts the defensive copy
     iff the operand is not donatable, so skipped slabs are correct either
     way).
2. **Grid form (fallback)** — the round-5 kernel: grid (N, G//tile,
   C-chunk) with automatically pipelined (Cb, tile) blocks; every slab
   crosses HBM read+write once. Selected by `RAFT_SCATTER_GRID=1`, by the
   sticky module flag FORCE_GRID (bench.py flips it if the DMA form is
   ever rejected by Mosaic, so one failed compile degrades the stage
   instead of killing it), or when the DMA form has no valid chunking.

Unlike ops/deep_gather.py (Mosaic's tpu.dynamic_gather 8-row limit), both
forms use only compare/select primitives plus (for the DMA form) local
async copies, so they compile on real TPU. Caller contract: duplicate rows
within a lane must already be resolved to identical values (the engine's
chronological resolution pass), making the application order irrelevant.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_I32 = jnp.int32
_G_TILES = (512, 256, 128)

# Escape hatch: force the XLA put_along_axis fallback (differential tests
# pin kernel-vs-puts equality through this; also a field kill switch).
DISABLE = bool(os.environ.get("RAFT_DISABLE_SCATTER_KERNEL"))


def env_force_grid() -> bool:
    """RAFT_SCATTER_GRID parsed as a real flag: '0'/'false'/'' mean OFF
    (a plain truthiness test would read RAFT_SCATTER_GRID=0 — an operator
    explicitly requesting the DMA form — as forcing the grid form)."""
    return os.environ.get(
        "RAFT_SCATTER_GRID", "").lower() not in ("", "0", "false")


# Escape hatch for the DMA form only: fall back to the round-5 grid form.
# STICKY when set by bench.py's candidate ladder — a Mosaic rejection of
# the DMA form on some future backend downgrades every later build in the
# process rather than failing the whole deep stage.
FORCE_GRID = env_force_grid()


def _chunk(C: int, tile: int, itemsize: int, n_bufs: int = 6):
    """Largest divisor of C that keeps the live (Cb, tile) slabs of BOTH
    arrays (~`n_bufs` block-sized buffers: in + aliased out + row/val
    blocks for the grid form; 2 slots x 2 arrays + row/val blocks for the
    DMA form) inside the Mosaic scoped-VMEM budget; sublane blocks must be
    multiples of 8 (ops/deep_gather._chunk). The cap scales INVERSELY with
    the lane tile AND the log dtype width — at int16/tile 512 a 2000-row
    chunk is ~12 MB of live blocks and Mosaic rejects the kernel (observed
    on hardware at G=12 800)."""
    cap = min(C, 2000, max(8, int(10e6 / (n_bufs * itemsize * tile))))
    for d in range(cap, 7, -1):
        if C % d == 0 and d % 8 == 0:
            return d
    return None


def _tile(G: int, interpret: bool):
    if interpret:
        return G
    for t in _G_TILES:
        if G % t == 0:
            return t
    return None


def _build_scatter_grid(N: int, C: int, K: int, ldt, G: int, tile: int,
                        interpret: bool):
    """Round-5 grid form: (node, G-tile, C-chunk) grid, every slab crosses
    HBM once via the automatic block pipeliner. Returns (call, Kp) or
    None."""
    Cb = _chunk(C, tile, ldt.itemsize)
    if Cb is None:
        return None
    n_chunks = C // Cb
    Kp = -(-K // 8) * 8  # sublane-aligned row-block height

    def kernel(rows_ref, vt_ref, vc_ref, lt_ref, lc_ref, ot_ref, oc_ref):
        c = pl.program_id(2)
        j0 = c * Cb
        rows = rows_ref[...]
        blk_t, blk_c = lt_ref[...], lc_ref[...]
        iot = lax.broadcasted_iota(_I32, (Cb, tile), 0) + j0
        for k in range(K):
            hit = iot == rows[k][None, :]  # row C never matches (iot < C)
            blk_t = jnp.where(hit, vt_ref[k][None, :], blk_t)
            blk_c = jnp.where(hit, vc_ref[k][None, :], blk_c)
        ot_ref[...] = blk_t
        oc_ref[...] = blk_c

    call = pl.pallas_call(
        kernel,
        grid=(N, G // tile, n_chunks),
        in_specs=[
            pl.BlockSpec((Kp, tile), lambda n, i, c: (n, i)),
            pl.BlockSpec((Kp, tile), lambda n, i, c: (n, i)),
            pl.BlockSpec((Kp, tile), lambda n, i, c: (n, i)),
            pl.BlockSpec((Cb, tile), lambda n, i, c: (n * n_chunks + c, i)),
            pl.BlockSpec((Cb, tile), lambda n, i, c: (n * n_chunks + c, i)),
        ],
        out_specs=[
            pl.BlockSpec((Cb, tile), lambda n, i, c: (n * n_chunks + c, i)),
            pl.BlockSpec((Cb, tile), lambda n, i, c: (n * n_chunks + c, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N * C, G), ldt),
            jax.ShapeDtypeStruct((N * C, G), ldt),
        ],
        input_output_aliases={3: 0, 4: 1},
        interpret=interpret,
    )
    return call, Kp


def _build_scatter_dma(N: int, C: int, K: int, ldt, G: int, tile: int,
                       interpret: bool):
    """DMA form: grid (node, G-tile); the chunk loop runs inside the kernel
    over logs left in HBM, with per-chunk any-hit skipping and a depth-1
    double-buffered pipeline (see module docstring). Returns (call, Kp) or
    None."""
    # Live VMEM: 2 slots x 2 arrays of (Cb, tile) ldt + the 3 (Kp, tile)
    # row/val blocks — model it as 4 block buffers + slack.
    Cb = _chunk(C, tile, ldt.itemsize, n_bufs=5)
    if Cb is None:
        return None
    n_chunks = C // Cb
    Kp = -(-K // 8) * 8

    def kernel(rows_ref, vt_ref, vc_ref, lt_hbm, lc_hbm, ot_hbm, oc_hbm,
               st_buf, sc_buf, sems):
        n = pl.program_id(0)
        i = pl.program_id(1)
        r0 = n * C          # this node's first global log row
        c0 = i * tile       # this tile's first lane column
        rows = rows_ref[...]
        # Per-chunk demand: does ANY lane of this tile write into chunk c?
        # Dropped rows carry C and land in no chunk (c*Cb + Cb <= C).
        hits = [jnp.any((rows >= c * Cb) & (rows < (c + 1) * Cb))
                for c in range(n_chunks)]

        def start_in(c, slot):
            for hbm, buf, a in ((lt_hbm, st_buf, 0), (lc_hbm, sc_buf, 1)):
                pltpu.make_async_copy(
                    hbm.at[pl.ds(r0 + c * Cb, Cb), pl.ds(c0, tile)],
                    buf.at[slot], sems.at[slot, a, 0]).start()

        def wait_in(c, slot):
            for hbm, buf, a in ((lt_hbm, st_buf, 0), (lc_hbm, sc_buf, 1)):
                pltpu.make_async_copy(
                    hbm.at[pl.ds(r0 + c * Cb, Cb), pl.ds(c0, tile)],
                    buf.at[slot], sems.at[slot, a, 0]).wait()

        def start_out(c, slot):
            for hbm, buf, a in ((ot_hbm, st_buf, 0), (oc_hbm, sc_buf, 1)):
                pltpu.make_async_copy(
                    buf.at[slot],
                    hbm.at[pl.ds(r0 + c * Cb, Cb), pl.ds(c0, tile)],
                    sems.at[slot, a, 1]).start()

        def wait_out(c, slot):
            for hbm, buf, a in ((ot_hbm, st_buf, 0), (oc_hbm, sc_buf, 1)):
                pltpu.make_async_copy(
                    buf.at[slot],
                    hbm.at[pl.ds(r0 + c * Cb, Cb), pl.ds(c0, tile)],
                    sems.at[slot, a, 1]).wait()

        @pl.when(hits[0])
        def _prologue():
            start_in(0, 0)

        # Per-slot drain bookkeeping (static, unrolled): pending[slot] is
        # the LAST chunk whose write-back was started from that slot. Every
        # started out-DMA is waited EXACTLY once — before the slot's next
        # reuse, or in the epilogue — under the same hits[] predicate that
        # started it, so no in-flight DMA or signaled semaphore can leak
        # across grid steps regardless of how sparse the hit pattern is
        # (the earlier scheme drained only under hits[c+1] & hits[c-1] and
        # left middle-chunk write-backs undrained on sparse patterns).
        pending = {}
        for c in range(n_chunks):
            slot, nslot = c % 2, (c + 1) % 2
            if c + 1 < n_chunks:
                # Drain the other slot's previous occupant before ANY
                # reuse, then prefetch chunk c+1 into it while chunk c
                # computes below.
                p = pending.pop(nslot, None)
                if p is not None:
                    @pl.when(hits[p])
                    def _drain(p=p, nslot=nslot):
                        wait_out(p, nslot)

                @pl.when(hits[c + 1])
                def _prefetch(c=c, nslot=nslot):
                    start_in(c + 1, nslot)

            @pl.when(hits[c])
            def _process(c=c, slot=slot):
                wait_in(c, slot)
                iot = lax.broadcasted_iota(_I32, (Cb, tile), 0) + c * Cb
                blk_t, blk_c = st_buf[slot], sc_buf[slot]
                for k in range(K):
                    hit = iot == rows[k][None, :]
                    blk_t = jnp.where(hit, vt_ref[k][None, :], blk_t)
                    blk_c = jnp.where(hit, vc_ref[k][None, :], blk_c)
                st_buf[slot] = blk_t
                sc_buf[slot] = blk_c
                start_out(c, slot)

            pending[slot] = c  # outstanding iff hits[c] (matched wait)

        # Epilogue: drain whatever is still outstanding on either slot —
        # the next grid step reuses both.
        for slot, p in sorted(pending.items()):
            @pl.when(hits[p])
            def _finish(p=p, slot=slot):
                wait_out(p, slot)

    call = pl.pallas_call(
        kernel,
        grid=(N, G // tile),
        in_specs=[
            pl.BlockSpec((Kp, tile), lambda n, i: (n, i)),
            pl.BlockSpec((Kp, tile), lambda n, i: (n, i)),
            pl.BlockSpec((Kp, tile), lambda n, i: (n, i)),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N * C, G), ldt),
            jax.ShapeDtypeStruct((N * C, G), ldt),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, Cb, tile), ldt),
            pltpu.VMEM((2, Cb, tile), ldt),
            pltpu.SemaphoreType.DMA((2, 2, 2)),  # (slot, array, direction)
        ],
        input_output_aliases={3: 0, 4: 1},
        interpret=interpret,
    )
    return call, Kp


@functools.lru_cache(maxsize=None)
def build_scatter(N: int, C: int, K: int, ldt_name: str, G: int,
                  interpret: bool, dma: bool = True):
    """-> callable(log_term (N*C, G) ldt, log_cmd (N*C, G) ldt,
                   rows (N*K, G) i32 LOCAL slots ([0, C); C = dropped),
                   vals_t (N*K, G) ldt, vals_c (N*K, G) ldt)
       -> (log_term', log_cmd') with per-lane writes applied.
    `dma=False` pins the round-5 grid form (tests; bench's degraded-mode
    candidate). Returns None when no supported tiling exists (caller falls
    back to XLA scatters)."""
    ldt = jnp.dtype(ldt_name)
    tile = _tile(G, interpret)
    if tile is None:
        return None
    built = None
    if dma:
        built = _build_scatter_dma(N, C, K, ldt, G, tile, interpret)
    if built is None:
        built = _build_scatter_grid(N, C, K, ldt, G, tile, interpret)
    if built is None:
        return None
    call, Kp = built

    def padded_call(lt, lc, rows, vals_t, vals_c):
        def pad(r, fill):
            r3 = r.reshape(N, K, G)
            z = jnp.full((N, Kp - K, G), fill, r.dtype)
            return jnp.concatenate([r3, z], axis=1).reshape(N * Kp, G)

        # Pad rows with C ("dropped") so the extra sublanes write nothing.
        return call(pad(rows, C), pad(vals_t, 0), pad(vals_c, 0), lt, lc)

    if Kp == K:
        return lambda lt, lc, rows, vt, vc: call(rows, vt, vc, lt, lc)
    return padded_call
