"""Pallas one-hot log-write kernel — the deep engine's scatter alternative.

The batched deep engine ends each tick with 2 XLA scatters (term + cmd)
applying ~K resolved rows per node (ops/tick.py deferred writes). The
round-5 probe model: an XLA scatter's cost scales with OPERAND SIZE (it
materializes a copy unless the while-body donates in place), and even the
donated in-context form pays tens of ms at config-5 scale. This kernel
applies BOTH arrays' writes in ONE pass over the logs:

- grid (node, C-chunk, G-tile); each step DMAs one (Cb, tile) slab of
  log_term AND log_cmd (the whole log crosses HBM exactly once, read +
  write, ~9 ms at config-5 scale);
- the write is applied as a K-deep one-hot select chain over the slab:
  `iota + chunk_offset == row` — compare shared by term and cmd (the two
  arrays write the same rows by construction). K is SMALL (~N+1 per node),
  so the VPU cost (K * C * G compares/selects) stays a few ms — the
  regime where one-hot beats gather/scatter lowering. (READS are the
  opposite: R~36 rows/node makes a one-hot read stream VPU-bound, which is
  why the read side uses XLA takes — ops/deep_gather.py docstring.)
- rows are LOCAL slot indices in [0, C); row == C means "dropped" (masked
  write) and matches no slab row.

Unlike ops/deep_gather.py (Mosaic's tpu.dynamic_gather 8-row limit), this
kernel uses only compare/select primitives, so it compiles on real TPU.
Caller contract: duplicate rows within a lane must already be resolved to
identical values (the engine's chronological resolution pass), making the
application order irrelevant.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

_I32 = jnp.int32
_G_TILES = (512, 256, 128)

# Escape hatch: force the XLA put_along_axis fallback (differential tests
# pin kernel-vs-puts equality through this; also a field kill switch).
DISABLE = bool(os.environ.get("RAFT_DISABLE_SCATTER_KERNEL"))


def _chunk(C: int, tile: int, itemsize: int):
    """Largest divisor of C that keeps the live (Cb, tile) slabs of BOTH
    arrays (in + aliased out + row/val blocks, ~6 block-sized buffers)
    inside the Mosaic scoped-VMEM budget; sublane blocks must be multiples
    of 8 (ops/deep_gather._chunk). The cap scales INVERSELY with the lane
    tile AND the log dtype width — at int16/tile 512 a 2000-row chunk is
    ~12 MB of live blocks and Mosaic rejects the kernel (observed on
    hardware at G=12 800)."""
    cap = min(C, 2000, max(8, int(10e6 / (6 * itemsize * tile))))
    for d in range(cap, 7, -1):
        if C % d == 0 and d % 8 == 0:
            return d
    return None


def _tile(G: int, interpret: bool):
    if interpret:
        return G
    for t in _G_TILES:
        if G % t == 0:
            return t
    return None


@functools.lru_cache(maxsize=None)
def build_scatter(N: int, C: int, K: int, ldt_name: str, G: int,
                  interpret: bool):
    """-> callable(log_term (N*C, G) ldt, log_cmd (N*C, G) ldt,
                   rows (N*K, G) i32 LOCAL slots ([0, C); C = dropped),
                   vals_t (N*K, G) ldt, vals_c (N*K, G) ldt)
       -> (log_term', log_cmd') with per-lane writes applied.
    Returns None when no supported tiling exists (caller falls back to XLA
    scatters)."""
    ldt = jnp.dtype(ldt_name)
    tile = _tile(G, interpret)
    if tile is None:
        return None
    Cb = _chunk(C, tile, ldt.itemsize)
    if Cb is None:
        return None
    n_chunks = C // Cb
    Kp = -(-K // 8) * 8  # sublane-aligned row-block height

    def kernel(rows_ref, vt_ref, vc_ref, lt_ref, lc_ref, ot_ref, oc_ref):
        c = pl.program_id(2)
        j0 = c * Cb
        rows = rows_ref[...]
        blk_t, blk_c = lt_ref[...], lc_ref[...]
        iot = lax.broadcasted_iota(_I32, (Cb, tile), 0) + j0
        for k in range(K):
            hit = iot == rows[k][None, :]  # row C never matches (iot < C)
            blk_t = jnp.where(hit, vt_ref[k][None, :], blk_t)
            blk_c = jnp.where(hit, vc_ref[k][None, :], blk_c)
        ot_ref[...] = blk_t
        oc_ref[...] = blk_c

    call = pl.pallas_call(
        kernel,
        grid=(N, G // tile, n_chunks),
        in_specs=[
            pl.BlockSpec((Kp, tile), lambda n, i, c: (n, i)),
            pl.BlockSpec((Kp, tile), lambda n, i, c: (n, i)),
            pl.BlockSpec((Kp, tile), lambda n, i, c: (n, i)),
            pl.BlockSpec((Cb, tile), lambda n, i, c: (n * n_chunks + c, i)),
            pl.BlockSpec((Cb, tile), lambda n, i, c: (n * n_chunks + c, i)),
        ],
        out_specs=[
            pl.BlockSpec((Cb, tile), lambda n, i, c: (n * n_chunks + c, i)),
            pl.BlockSpec((Cb, tile), lambda n, i, c: (n * n_chunks + c, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N * C, G), ldt),
            jax.ShapeDtypeStruct((N * C, G), ldt),
        ],
        input_output_aliases={3: 0, 4: 1},
        interpret=interpret,
    )

    def padded_call(lt, lc, rows, vals_t, vals_c):
        def pad(r, fill):
            r3 = r.reshape(N, K, G)
            z = jnp.full((N, Kp - K, G), fill, r.dtype)
            return jnp.concatenate([r3, z], axis=1).reshape(N * Kp, G)

        # Pad rows with C ("dropped") so the extra sublanes write nothing.
        return call(pad(rows, C), pad(vals_t, 0), pad(vals_c, 0), lt, lc)

    if Kp == K:
        return lambda lt, lc, rows, vt, vc: call(rows, vt, vc, lt, lc)
    return padded_call
