"""The vectorized lockstep tick: all (groups x nodes) advance one SEMANTICS.md tick
inside one jitted, scan-able pure function.

Design (TPU-first, not a port): the reference's threads/timers/RPCs (RaftServer.kt)
become a fixed phase pipeline of elementwise (G,)-wide integer ops — the node loops are
tiny (N ≤ 9) and unrolled at trace time, so group count G is the only data axis and XLA
sees static shapes throughout. State is laid out groups-minor ((N, G), (N, N, G),
(N, C, G) — models/state.py) so every per-node access is a contiguous lane-aligned row.
RPC exchanges are in-array mailbox transactions: each (candidate, peer) /
(leader, peer) pair is one masked vectorized read-modify-write over the G axis, applied
sequentially in the canonical order so the result is bit-identical to the scalar oracle
(models/oracle.py). Quorum tallies are reductions over the node axis.

The tick is split into two pieces so one implementation of the protocol serves two
compilation paths:
- `phase_body(cfg, s, aux, flags)` — the ENTIRE phase lattice (F, 0-5) as pure jnp ops
  on a dict of (N, G)-shaped values. It consumes NO randomness: every draw it needs
  arrives pre-drawn in `aux` (all derivable from pre-tick state, except the deferred
  election draws which it reports back via the returned el_dirty mask).
- `make_tick(cfg)` — the XLA wrapper: draws the aux inputs (counted threefry,
  utils/rng.py, canonical (G, ...) shapes transposed at the boundary), runs
  phase_body, then materializes the deferred election-timer draws.
The Pallas megakernel (ops/pallas_tick.py) wraps the SAME phase_body, so the two
backends are bit-identical by construction.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from raft_kotlin_tpu.models.state import (
    ACTIVE,
    BACKOFF,
    CANDIDATE,
    DIGEST_MULT,
    FOLLOWER,
    IDLE,
    LEADER,
    MAILBOX_FIELDS,
    SNAPSHOT_FIELDS,
    RaftState,
    enter_packed_compute,
    exit_packed_compute,
    popcount32,
)
from raft_kotlin_tpu.utils import rng as rngmod
from raft_kotlin_tpu.utils import telemetry as telemetry_mod
from raft_kotlin_tpu.utils.config import RaftConfig

_I32 = jnp.int32

# The CORE phase_body state fields, in canonical order (everything except the tick
# scalar, the optional §10 mailbox fields, and the optional §15 snapshot
# fields — see state_fields()).
STATE_FIELDS = tuple(
    f.name for f in dataclasses.fields(RaftState)
    if f.name != "tick" and f.name not in MAILBOX_FIELDS
    and f.name not in SNAPSHOT_FIELDS
)


def state_fields(flags: "BodyFlags") -> tuple:
    """The state fields phase_body operates on under `flags`: the core set,
    plus the §10 mailbox slots when the delay path is compiled in, plus
    the §15 snapshot fields when compaction is compiled in."""
    return (STATE_FIELDS + (MAILBOX_FIELDS if flags.delay else ())
            + (SNAPSHOT_FIELDS if flags.compact else ()))


# Pre-drawn randomness + driver inputs consumed by phase_body.
AUX_FIELDS = (
    "edge_iid",    # (N*N, G) bool — §4 iid survival, row (s-1)*N + r-1
    "crash_m",     # (N, G) bool — §9 crash events (random ∨ driver cmd)
    "restart_m",   # (N, G) bool
    "link_fail",   # (N*N, G) bool
    "link_heal",   # (N*N, G) bool
    "el_draw_f",   # (N, G) i32 — timeout draw at pre-tick t_ctr (phase-F restarts)
    "bdraw",       # (N, G) i32 — backoff draw at pre-tick b_ctr (phase 4)
    "periodic",    # (1, G) i32 — phase-0 workload command value, -1 = none
    "inject",      # (N, G) i32 — driver commands, -1 = none
    "delay",       # (N*N, G) i32 — §10 per-pair send delays (only when lo < hi)
)



# The phase lattice works exclusively on RANK-2 (rows, G) arrays: (N, G) per-node
# grids, (N*N, G) flattened pair grids (row = (a-1)*N + b-1), (N*C, G) flattened logs
# (row = (n-1)*C + slot). Rationale: Pallas/Mosaic TC kernels implement neither
# scatter nor dynamic_update_slice on values and mishandle rank-3 i1 vectors, so all
# static-index updates are one-hot row selects (iota + compare + where — primitives
# both XLA and Mosaic handle; XLA folds the constant one-hots) and rank never
# exceeds 2. Flattening (N, N, G) -> (N*N, G) at the wrapper boundary is free.
_PAIR_FIELDS = ("responded", "next_index", "match_index", "link_up") + MAILBOX_FIELDS
_LOG_FIELDS = ("log_term", "log_cmd")


def _set_row(arr, i, vals):
    """arr[i] = vals for a static row index i; vals has arr.shape[1:].
    Bool arrays route through int32: Mosaic lowers select-of-i1-VALUES via an i8
    widening it then cannot truncate back (i1 conditions are fine)."""
    if arr.dtype == jnp.bool_:
        return _set_row(arr.astype(_I32), i, vals.astype(_I32)) != 0
    hot = lax.broadcasted_iota(_I32, arr.shape, 0) == i
    return jnp.where(hot, vals[None], arr)


def _rep_rows(vals, N):
    """(N, G) -> (N*N, G) owner replication: output row (a-1)*N + b-1 carries
    vals[a-1] — the pair-grid broadcast `vals[:, None, :]`, built rank-2-only.
    Bool inputs concatenate as int32 and compare back: Mosaic lowers i1 concat
    through an i8 widening it then cannot truncate."""
    if vals.dtype == jnp.bool_:
        return _rep_rows(vals.astype(_I32), N) != 0
    return jnp.concatenate(
        [jnp.broadcast_to(vals[a][None], (N,) + vals.shape[1:]) for a in range(N)],
        axis=0,
    )


def _tree_reduce(op, terms):
    """Balanced fold of an ASSOCIATIVE `op` over `terms`: ceil(log2 n) op
    depth instead of the linear left-fold chain Python's sum()/reduce()
    build. Used on the tick's critical path (ISSUE 4 chain shortening);
    bit-exact for the ops it is applied to here (integer add, boolean or —
    associative and commutative, so any association yields the same bits)."""
    terms = list(terms)
    assert terms
    while len(terms) > 1:
        nxt = [op(terms[i], terms[i + 1])
               for i in range(0, len(terms) - 1, 2)]
        if len(terms) % 2:
            nxt.append(terms[-1])
        terms = nxt
    return terms[0]


def _kth_largest(terms, k):
    """Per-lane k-th largest (1-based) of the (G,)-valued `terms`, via a
    bitonic sorting network of jnp.minimum/maximum pairs — O(log^2 n) op
    DEPTH. This is the chain-shortening form of the phase-5 quorum test:
    #{v : v > c} >= k  <=>  kth_largest(values) > c (exact for integers),
    which moves the O(n)-deep accumulate-and-count chain OFF the leader's
    commit cell — the network depends only on the match_index rows, and the
    commit chain grows by one compare + one select per exchange instead of
    the whole tally. Padding uses the dtype's minimum, which sorts below
    every real value (match_index is always >= 0)."""
    n = len(terms)
    assert 1 <= k <= n
    p = 1 << (n - 1).bit_length()
    if p > n:
        sent = jnp.full(terms[0].shape, jnp.iinfo(terms[0].dtype).min,
                        terms[0].dtype)
        terms = list(terms) + [sent] * (p - n)
    a = list(terms)
    kk = 2
    while kk <= p:
        j = kk // 2
        while j >= 1:
            for i in range(p):
                m = i ^ j
                if m > i:
                    lo = jnp.minimum(a[i], a[m])
                    hi = jnp.maximum(a[i], a[m])
                    a[i], a[m] = (lo, hi) if (i & kk) == 0 else (hi, lo)
            j //= 2
        kk *= 2
    return a[p - k]  # ascending order: slot p - k is the k-th largest


@dataclasses.dataclass(frozen=True)
class BodyFlags:
    """Static switches: which optional phases the compiled body includes."""
    faults: bool = False
    links: bool = False
    periodic: bool = False
    inject: bool = False
    delay: bool = False  # §10 mailbox exchanges (cfg.uses_mailbox)
    # Deep-log addressing mode: True = log reads/writes via per-lane dynamic
    # gather/scatter (take/put_along_axis) instead of (N*C, G) one-hot masks.
    # The one-hot form is Mosaic's requirement (no scatter/gather in the
    # Pallas TC path) and is fine for small C, but at config-5 depth
    # (C=10_000) each one-hot is a ~100M-element intermediate and the tick
    # does ~6 per (node, peer) pair — gathers make deep logs feasible.
    # Values are identical either way (same slots, same masks).
    dyn_log: bool = False
    # Deep-log BATCHED engine (phase-5 reads in 2 takes per node + deferred
    # duplicate-resolved write scatters): the single-device deep-log fast
    # path. Under the §10 mailbox it additionally requires delay_lo >= 1 —
    # the KNOWN-DELIVERY regime (r7): every delivery then consumes a slot
    # filled on an EARLIER tick, so the whole phase-5 read set is
    # computable at tick start (delivery prevLog rows are the slots' own
    # aq_pli snapshots; a pair's next_index at its send is pre-tick ni + d
    # with d in {-1, 0, +1} decided solely by that pair's single delivery
    # this tick, so send reads live in the static window [ni-3, ni]). τ=0
    # (delay_lo == 0) keeps the per-pair engine: a slot can be filled AND
    # delivered within one tick, so no pre-computable read set exists.
    # Also off when the SPMD partitioner would see the program (sharded
    # runs route it through shard_map instead — parallel/mesh).
    batched: bool = False
    # True only for runs that are ACTUALLY sharded (parallel/mesh routes the
    # dyn tick through shard_map and sets this): the per-pair dyn engine then
    # keeps the logs FLAT — the round-2-proven sharded program. Single-device
    # per-pair dyn runs (the mailbox+deep corner) leave it False and get
    # per-node (C, G) slice operands, an ~Nx cut per log op.
    sharded: bool = False
    # §15 log compaction / snapshotting (cfg.uses_compaction): snapshot
    # state fields ride `s`, log addressing goes through the ring-window
    # translate (position -> slot = position mod C, valid in
    # [snap_index, snap_index + C)), phase 5 grows the InstallSnapshot
    # exchange, and a fold phase runs at tick end. False compiles the
    # bit-identical pre-§15 program (the migration-equality contract).
    # Mailbox compaction configs keep the per-pair engine: an install
    # delivery JUMPS next_index, breaking the known-delivery batched
    # engine's static row-window invariant.
    compact: bool = False
    # §18 packed-DOMAIN compute (SEMANTICS.md §18): the vote-exchange set
    # (responded/votes/responses) rides the lattice as two (N, G) int32
    # words — responded_bits (bit p-1 of row c-1 = pair (c, p) exchanged
    # this round) and vote_bits (granted subset) — and the phase-4 quorum
    # compare becomes one popcount per word. `s` must then carry
    # responded_bits/vote_bits INSTEAD of the three wide fields
    # (models/state.enter_packed_compute). Every other field stays wide
    # inside the lattice; engines pack the ctrl head and link plane only
    # across their own storage boundary. Bit-equal to the wide program on
    # every observable (the popcount identities, §18).
    packed_compute: bool = False


def phase_body(cfg: RaftConfig, s: dict, aux: dict, flags: BodyFlags,
               fcache: Optional[dict] = None, cut: Optional[int] = None):
    """Advance the phase lattice F,0-5 one tick, mutating `s` in place.

    `s` maps STATE_FIELDS to RANK-2 values: (N, G) per-node grids, (N*N, G) pair
    grids (_PAIR_FIELDS, row (a-1)*N + b-1), (N*C, G) logs (_LOG_FIELDS, row
    (n-1)*C + slot) — see flatten_state. Bool fields are real bools.
    `aux` maps AUX_FIELDS to values (only the ones the flags enable are read).
    Returns el_dirty (N, G) bool: nodes whose election timer reset in phases 2-5 and
    whose el_left must be materialized by the caller as the draw at t_ctr - 1
    (SEMANTICS.md §7 deferral — el_left's only reader is phase 1).

    `fcache` (batched engine only; ops/deep_cache.py): the frontier-value
    cache dict, mutated in place. When present, the phase-5 read batch is
    served from the cached frontier values plus one small budgeted refill
    take per log array instead of the full ~4N+1-rows-per-node takes, and
    an "ov" (G,) bool entry is ADDED to the dict: True where a needed value
    was unavailable (budget overflow / consumed-invalid) — the caller must
    then discard the tick's bits and re-run on the plain engine.

    `cut` truncates the lattice after phase `cut` (output bits then
    MEANINGLESS — analysis only): opcount's per-phase chain-depth
    attribution passes it explicitly; None reads the RAFT_PHASE_CUT env
    var (scripts/probe_phase_cuts.py's on-hardware timing ablation).
    """
    # Phase-scoped profiler regions (ISSUE 5): every op traced in the
    # lattice carries a raft/<phase> name matching opcount.
    # phase_body_chain_depth's by-phase attribution keys, so Perfetto op
    # groups line up with the chain-depth model. Trace-time metadata only.
    # The try/finally restores the thread-local name stack even when
    # tracing aborts mid-lattice (e.g. an engine candidate rejected at
    # trace time) — a leaked scope would prefix every later trace's names.
    _ps = telemetry_mod.PhaseScopes()
    try:
        return _phase_lattice(cfg, s, aux, flags, fcache, cut, _ps)
    finally:
        _ps.close()


def _phase_lattice(cfg: RaftConfig, s: dict, aux: dict, flags: BodyFlags,
                   fcache: Optional[dict], cut: Optional[int], _ps):
    """phase_body's lattice (all semantics documented there); `_ps` is the
    caller-owned profiler scope manager, closed by the caller.

    C here is the PHYSICAL log window (§16 cfg.phys_capacity): the ring
    translate, the window-validity test, the capacity clip and every
    per-node log slice address physical rows. Logical positions
    (last_index/commit/next_index/...) are bounded by this C only
    without compaction; with it they are unbounded i32 and only their
    ring image lands in [0, C)."""
    N, C, maj = cfg.n_nodes, cfg.phys_capacity, cfg.majority
    G = s["term"].shape[-1]
    pc = flags.packed_compute  # §18 packed-domain vote-exchange set
    # Probe-only phase ablation (scripts/probe_phase_cuts.py): compile the
    # lattice cut after phase k — output bits are then MEANINGLESS; used
    # exclusively for per-phase timing attribution on hardware. Read at trace
    # time so probes can sweep without reloading the module. A leftover env
    # var (probe crash) would silently poison every later compile, so any
    # active cut is announced LOUDLY at trace time (r4 ADVICE). An EXPLICIT
    # `cut` (opcount's by-phase attribution) skips the warning — the caller
    # asked for the truncation and never runs the bits.
    if cut is None:
        cut = int(os.environ.get("RAFT_PHASE_CUT", "99"))
        if cut < 99:
            import warnings

            warnings.warn(
                f"RAFT_PHASE_CUT={cut} is active: this tick is compiled with "
                "the phase lattice TRUNCATED and its output bits are "
                "meaningless. Probe-only — unset RAFT_PHASE_CUT for real "
                "simulations.",
                stacklevel=2)

    _ps.enter("F0")

    # Logs live as PER-NODE (C, G) slices for the duration of the phase
    # lattice (static slices of the flat (N*C, G) layout — free in XLA,
    # supported value ops in Mosaic). Every log op then touches a C-row
    # operand instead of N*C — an Nx cut in the dominant cost of the tick —
    # and an out-of-range index structurally CANNOT alias another node's
    # rows: it simply matches nothing in [0, C).
    #
    # EXCEPT the per-pair dyn engine on ACTUALLY SHARDED runs (flags.sharded,
    # set by parallel/mesh): there the logs stay FLAT with global rows — the
    # slice + per-slice scatter + concat pattern makes XLA's SPMD partitioner
    # blow up (observed: SIGABRT / unbounded HLO-pass memory on the CPU
    # backend), and the flat per-pair form is the round-2-proven sharded
    # program. A SINGLE-DEVICE per-pair dyn run (the mailbox+deep corner)
    # keeps slices: same values (differentially tested), ~Nx less log-op cost
    # (bench.py's mailbox-deep probe carries the number).
    use_slices = (not flags.dyn_log) or flags.batched or not flags.sharded
    if use_slices:
        lt = [s["log_term"][n * C:(n + 1) * C] for n in range(N)]
        lc = [s["log_cmd"][n * C:(n + 1) * C] for n in range(N)]

    # Deep-log batched engine (XLA-only; Mosaic never sees dyn_log). Measured
    # cost model on TPU (v5e, C=10k, G=13k): a take/put costs the SAME for 1
    # or 64 index rows — per-OP x operand-size, not per-row. The per-pair
    # engine issues ~7 single-row ops per (l, p) pair = ~350 log-sized ops
    # per tick; this mode instead (a) batches ALL phase-5 reads into 2 takes
    # per node up front (row indices are known post-phase-4: in the
    # non-mailbox path next_index[pair(l, p)] is only mutated by its own
    # exchange), (b) DEFERS every phase-5 log write into a per-node pending
    # list, applying it at end of phase as one duplicate-resolved scatter
    # per node per array, and (c) overlays pending writes onto batched reads
    # at consume time (patch), preserving the canonical pair-order semantics
    # bit-for-bit. The mailbox path interleaves deliveries with sends, but
    # for delay_lo >= 1 every delivery is KNOWN at tick start and each
    # pair's next_index moves by at most its own delivery's ±1 before its
    # send — so the batch widens to a 4-candidate row window per pair plus
    # the slots' own aq_pli snapshot rows and stays computable up front
    # (see the mailbox branch of the batch builder below); only τ=0 keeps
    # the per-pair engine.
    batched_logs = flags.batched
    if batched_logs and flags.delay:
        assert cfg.known_delivery, (
            "batched deep engines under the mailbox need the known-delivery "
            "regime (delay_lo >= 1); τ=0 configs keep the per-pair engine")
        assert not flags.compact, (
            "mailbox compaction configs keep the per-pair engine: an "
            "InstallSnapshot delivery jumps next_index, breaking the "
            "known-delivery batched row-window invariant (SEMANTICS.md §15)")
    # §15 compaction setup: the ring translate + window test every log
    # access routes through (THE shared translate-or-latch index map), and
    # the watermark/chunk constants of the end-of-tick fold phase.
    compact = flags.compact
    if compact:
        assert fcache is None, (
            "the frontier-cache engine does not support §15 compaction "
            "(plan_for routes compaction configs to batched/flat)")
        W_cmp, CH_cmp = cfg.compact_watermark, cfg.compact_chunk

        def ring(pos):
            # Ring slot of a position: pos mod C via lax.rem (C-style
            # truncation — a NEGATIVE position stays negative and matches
            # no log row, the non-compact out-of-range convention).
            return lax.rem(pos.astype(_I32), C)

        def _win_ok(n, idx):
            # The translate-or-latch window test: positions below the
            # node's snapshot base are FOLDED (readable only as snap_term
            # at base-1 / via InstallSnapshot); at/above base + C they
            # would alias a live slot.
            b = col("snap_index", n).astype(_I32)
            i32 = idx.astype(_I32)
            return (i32 >= b) & (i32 < b + C)
    logrow_c = None if flags.dyn_log else jax.lax.broadcasted_iota(_I32, (C, G), 0)
    # The columnar view pays off inside the Mosaic megakernel (grid rebuilds
    # measured ~31% of it); deep-log (dyn) configs are XLA-only, where the
    # fusion compiler already folds the rebuilds — and the columnar
    # stack/split pattern combined with dyn gather/scatter trips an XLA:CPU
    # SPMD-partitioner abort on sharded runs. Grid mode for dyn configs.
    use_columnar = not flags.dyn_log

    use_fc = batched_logs and fcache is not None
    if batched_logs:
        # node -> chronological [(local_rows (G,), term_v, cmd_v, wr)] of
        # deferred phase-0/5 writes; values kept int32, narrowed at
        # patch/apply. Where the write mask is off, the row is C — OUT OF
        # RANGE — and the final scatter drops it (mode="drop"), so masked
        # lanes need no current-value resolution at all.
        pending = {n: [] for n in range(1, N + 1)}
        defer = {"on": False}
        ldt_b = lt[0].dtype

        def rt(v):
            # Storage-dtype roundtrip: cache values must equal what a read
            # AFTER the (narrowing) store would see.
            return v.astype(ldt_b).astype(_I32)

        if use_fc:
            from raft_kotlin_tpu.ops import deep_cache

            # Unstack the cache to per-row lists for cheap (G,) updates in
            # the pair loop (the columnar-view trick); restacked at exit.
            # Known-delivery mailbox configs carry the extra second-entry
            # window fields (deep_cache.PAIR_VALS_MB).
            fc_fields = deep_cache.fields_for(flags.delay)
            fc_pvals = deep_cache.pair_vals_for(flags.delay)
            fcl = {k: [fcache[k][i] for i in range(fcache[k].shape[0])]
                   for k in fc_fields}
            fc_ov = {"v": jnp.zeros((G,), dtype=bool)}

            def fc_patch_write(n, wr, slot, term_v, cmd_v):
                """A deferred write of (term_v, cmd_v) at n's physical
                `slot` (mask wr) patches every cache entry whose (log, row)
                it hits — value AND validity (a write fully determines the
                row's content)."""
                tv, cv = rt(term_v), rt(cmd_v)
                for q in range(1, N + 1):
                    pi = pair(n, q)
                    niq = prow("next_index", n, q).astype(_I32)
                    # Merged overlay masks (r6): f_ent_t and f_ent_c live at
                    # the same row (ni - 1), so the three keys share two hit
                    # compares instead of computing three.
                    hit2 = wr & (slot == niq - 2)
                    hit1 = wr & (slot == niq - 1)
                    targets = [("f_pli", hit2, tv),
                               ("f_ent_t", hit1, tv),
                               ("f_ent_c", hit1, cv)]
                    if flags.delay:
                        # Second-entry window: row ni (PAIR_VALS_MB).
                        hit0 = wr & (slot == niq)
                        targets += [("f_ent2_t", hit0, tv),
                                    ("f_ent2_c", hit0, cv)]
                    for key, hit, val in targets:
                        fcl[key][pi] = jnp.where(hit, val, fcl[key][pi])
                        okk = deep_cache.ok_name(key)
                        fcl[okk][pi] = fcl[okk][pi] | hit
                for l2 in range(1, N + 1):
                    pi = pair(l2, n)
                    nil = prow("next_index", l2, n).astype(_I32)
                    hit = wr & (slot == nil - 2)
                    fcl["f_ppli"][pi] = jnp.where(hit, tv, fcl["f_ppli"][pi])
                    fcl["ok_ppli"][pi] = fcl["ok_ppli"][pi] | hit

        def patch(name, node, row, v):
            """Overlay node's pending (deferred) writes onto a raw gather of
            local row `row` — the value a read AFTER those writes must see.
            Values roundtrip the storage dtype so an int16 wrap patches
            identically to a real store."""
            for prow, pt, pc, pwr in pending[node]:
                pv = pt if name == "log_term" else pc
                pv = pv.astype(ldt_b).astype(_I32)
                v = jnp.where(pwr & (prow == row), pv, v)
            return v

    def pair(a, b):
        # Flat pair-grid row for (owner a, peer b), both 1-based.
        return (a - 1) * N + (b - 1)

    # Columnar view for phases 3/5 (the per-pair exchange loops): node fields
    # live as N separate (G,) rows and pair fields as N*N rows, so a per-pair
    # update is ONE (G,) select instead of a full-grid rebuild (the iota-
    # compare _set_row pattern measured ~31% of the megakernel's runtime).
    # Grid phases (F, 0-2, 4) run on the stacked (N, G)/(N*N, G) arrays as
    # before; enter_cols()/exit_cols() convert at the phase boundaries (a
    # handful of stacks — far cheaper than per-update rebuilds). Bool fields
    # in the view (el_armed/hb_armed/up) are only ever combined with boolean
    # algebra, never select-of-i1-values (Mosaic limits).
    _COLF = ("term", "voted_for", "role", "commit", "last_index", "phys_len",
             "last_term", "el_armed", "round_state", "round_age",
             "hb_armed", "hb_left", "up", "t_ctr", "rounds", "cap_ov") \
        + (("responded_bits", "vote_bits") if pc else ("votes", "responses")) \
        + (SNAPSHOT_FIELDS if flags.compact else ())
    _PAIRV = (() if pc else ("responded",)) + ("next_index", "match_index") + \
        (MAILBOX_FIELDS if flags.delay else ())
    view: dict = {}

    def enter_cols():
        for k in _COLF:
            view[k] = [s[k][i] for i in range(N)]
        for k in _PAIRV:
            view[k] = [s[k][i] for i in range(N * N)]

    def _stack_rows(rows):
        # Bool rows restack through int32: Mosaic lowers i1 concat via an i8
        # widening it cannot truncate back (same limitation as _rep_rows).
        if rows[0].dtype == jnp.bool_:
            return jnp.stack([r.astype(_I32) for r in rows]) != 0
        return jnp.stack(rows)

    def exit_cols():
        for k in _COLF + _PAIRV:
            s[k] = _stack_rows(view[k])
        view.clear()

    def col(name, n):
        if name in view:
            return view[name][n - 1]
        return s[name][n - 1]

    def setcol(name, n, mask, vals):
        if name in view:
            view[name][n - 1] = jnp.where(mask, vals, view[name][n - 1])
            return
        cur = s[name][n - 1]
        s[name] = _set_row(s[name], n - 1, jnp.where(mask, vals, cur))

    def prow(name, a, b):
        if name in view:
            return view[name][pair(a, b)]
        return s[name][pair(a, b)]

    def set_prow(name, a, b, vals):
        if name in view:
            view[name][pair(a, b)] = vals
            return
        s[name] = _set_row(s[name], pair(a, b), vals)

    def orcol(name, n, bits):
        # §18 packed-compute: OR `bits` into node n's packed word
        # (columnar when the view is active, grid-row rebuild otherwise).
        if name in view:
            view[name][n - 1] = view[name][n - 1] | bits
            return
        s[name] = _set_row(s[name], n - 1, s[name][n - 1] | bits)

    def responded_clear(c, p):
        # "pair (c, p) has not exchanged this round". The §18 packed test
        # reads bit p-1 of c's responded word; the wide test reads the
        # per-pair plane — the same bit by the §14 layout, including the
        # in-loop ordering (the packed OR is inline, like put_pair).
        if pc:
            return ((col("responded_bits", c) >> (p - 1)) & 1) == 0
        return prow("responded", c, p) == 0

    # Read addressing. All three engine forms route through the same §15
    # translate-or-latch discipline when flags.compact: `idx` is a LOGICAL
    # POSITION, its ring slot is ring(idx) = idx mod C, and validity is the
    # node's live window [snap_index, snap_index + C) (_win_ok) — with
    # snap_index == 0 (compaction off) this degenerates to the historical
    # [0, C) structural bound, and the non-compact branches below compile
    # the byte-identical pre-§15 program.
    if flags.dyn_log and use_slices:
        if compact:
            def log_gather(name, n, idx):
                rows = ring(jnp.maximum(idx.astype(_I32), 0))[None, :]
                v = jnp.take_along_axis(
                    (lt if name == "log_term" else lc)[n - 1], rows,
                    axis=0)[0]
                return jnp.where(_win_ok(n, idx), v, 0).astype(_I32)

            def log_gather_tc(n, idx):
                rows = ring(jnp.maximum(idx.astype(_I32), 0))[None, :]
                ok = _win_ok(n, idx)
                tv = jnp.take_along_axis(lt[n - 1], rows, axis=0)[0]
                cv = jnp.take_along_axis(lc[n - 1], rows, axis=0)[0]
                return (jnp.where(ok, tv, 0).astype(_I32),
                        jnp.where(ok, cv, 0).astype(_I32))
        else:
            def _gather1(arr, idx):
                v = jnp.take_along_axis(
                    arr, jnp.clip(idx, 0, C - 1)[None, :], axis=0)[0]
                return jnp.where((idx >= 0) & (idx < C), v, 0).astype(_I32)

            def log_gather(name, n, idx):
                # (G,) read of node n's physical slot idx via a per-lane
                # dynamic gather on its (C, G) log; 0 where idx is out of
                # [0, C).
                return _gather1((lt if name == "log_term" else lc)[n - 1],
                                idx)

            def log_gather_tc(n, idx):
                # (term, cmd) at one slot, sharing the clip/bounds work.
                rows = jnp.clip(idx, 0, C - 1)[None, :]
                ok = (idx >= 0) & (idx < C)
                tv = jnp.take_along_axis(lt[n - 1], rows, axis=0)[0]
                cv = jnp.take_along_axis(lc[n - 1], rows, axis=0)[0]
                return (jnp.where(ok, tv, 0).astype(_I32),
                        jnp.where(ok, cv, 0).astype(_I32))
    elif flags.dyn_log:
        # Per-pair dyn engine, FLAT addressing (global row (n-1)*C + slot).
        # The bounds terms are load-bearing here: an out-of-range idx in the
        # flat layout would otherwise alias an ADJACENT node's row.
        if compact:
            def log_gather(name, n, idx):
                rows = (n - 1) * C + ring(jnp.maximum(idx.astype(_I32), 0))
                v = jnp.take_along_axis(s[name], rows[None, :], axis=0)[0]
                return jnp.where(_win_ok(n, idx), v, 0).astype(_I32)

            def log_gather_tc(n, idx):
                rows = ((n - 1) * C
                        + ring(jnp.maximum(idx.astype(_I32), 0)))[None, :]
                ok = _win_ok(n, idx)
                tv = jnp.take_along_axis(s["log_term"], rows, axis=0)[0]
                cv = jnp.take_along_axis(s["log_cmd"], rows, axis=0)[0]
                return (jnp.where(ok, tv, 0).astype(_I32),
                        jnp.where(ok, cv, 0).astype(_I32))
        else:
            def log_gather(name, n, idx):
                rows = (n - 1) * C + jnp.clip(idx, 0, C - 1)
                v = jnp.take_along_axis(s[name], rows[None, :], axis=0)[0]
                return jnp.where((idx >= 0) & (idx < C), v, 0).astype(_I32)

            def log_gather_tc(n, idx):
                rows = ((n - 1) * C + jnp.clip(idx, 0, C - 1))[None, :]
                ok = (idx >= 0) & (idx < C)
                tv = jnp.take_along_axis(s["log_term"], rows, axis=0)[0]
                cv = jnp.take_along_axis(s["log_cmd"], rows, axis=0)[0]
                return (jnp.where(ok, tv, 0).astype(_I32),
                        jnp.where(ok, cv, 0).astype(_I32))
    elif compact:
        # One-hot form with the ring translate (Mosaic-compatible: rem by
        # a constant + compare; a negative position's rem stays negative
        # and matches no row, out-of-window matches are masked by _win_ok).
        def log_gather(name, n, idx):
            oh = logrow_c == ring(idx)[None, :]
            v = jnp.sum(jnp.where(oh, (lt if name == "log_term" else
                                       lc)[n - 1], 0), axis=0).astype(_I32)
            return jnp.where(_win_ok(n, idx), v, 0)

        def log_gather_tc(n, idx):
            oh = logrow_c == ring(idx)[None, :]
            ok = _win_ok(n, idx)
            tv = jnp.sum(jnp.where(oh, lt[n - 1], 0), axis=0).astype(_I32)
            cv = jnp.sum(jnp.where(oh, lc[n - 1], 0), axis=0).astype(_I32)
            return jnp.where(ok, tv, 0), jnp.where(ok, cv, 0)
    else:
        def _gather1(arr, idx):
            # One-hot contraction over (C, G) (no gather op — the
            # Mosaic-compatible form). An out-of-range idx matches no row, so
            # the 0-outside-[0,C) guarantee needs no explicit bounds term.
            oh = logrow_c == idx[None, :]
            # Widen at read: log storage may be int16 (cfg.log_dtype); the
            # one-hot sum has at most one nonzero per column, so summing in the
            # narrow dtype cannot overflow before the cast.
            return jnp.sum(jnp.where(oh, arr, 0), axis=0).astype(_I32)

        def log_gather(name, n, idx):
            return _gather1((lt if name == "log_term" else lc)[n - 1], idx)

        def log_gather_tc(n, idx):
            # (term, cmd) at one slot, sharing the one-hot mask.
            oh = logrow_c == idx[None, :]
            return (jnp.sum(jnp.where(oh, lt[n - 1], 0), axis=0).astype(_I32),
                    jnp.sum(jnp.where(oh, lc[n - 1], 0), axis=0).astype(_I32))

    def log_term_b(n, idx):
        """log_term at POSITION idx with the §15 snapshot boundary: the
        folded boundary row base-1 reads snap_term (base == 0 degenerates
        to the historical read — snap_term is then structurally 0 and
        idx == -1 callers mask it out themselves)."""
        v = log_gather("log_term", n, idx)
        if not compact:
            return v
        b = col("snap_index", n).astype(_I32)
        return jnp.where(idx.astype(_I32) == b - 1,
                         col("snap_term", n).astype(_I32), v)

    def log_add(n, i, term_v, cmd_v, mask):
        # SEMANTICS.md §3 add(): physical append / reject / overwrite-truncate.
        # The write slot is always in-range where the write mask holds (append
        # needs phys_len < C; overwrite needs i < last_index <= C).
        # §15 (compact): the capacity clip tests the LIVE WINDOW
        # phys_len - snap_index < C, overwrites below the snapshot base are
        # ABSORBED (already folded — a no-op reported as success by the
        # caller's own succ term), and slots are ring-translated.
        li = col("last_index", n)
        pl = col("phys_len", n)
        if compact:
            b_n = col("snap_index", n)
            has_room = (pl - b_n) < C
        else:
            has_room = pl < C
        # `mask` is the deepest input (it carries the exchange's succ/demote
        # chain) — joined LAST so the local compares issue ahead of it.
        # §15: the absorb rule tests the POSITION before any branch (the
        # RingLog.add order) — quirk-a lets commit outrun the leader's own
        # last_index, so an aggressive fold can push base past li and the
        # next APPEND (i == li < base) is folded content too: success,
        # no write, no li advance, and no capacity test (the oracle's
        # absorb returns before its clip).
        if compact:
            app = ((i == li) & (i >= b_n) & has_room) & mask
            ovw = ((i < li) & (i >= b_n)) & mask
        else:
            app = ((i == li) & has_room) & mask
            ovw = ((i < li) & (i >= 0)) & mask
        # §15 capacity-exhaustion latch (satellite 1): an append REJECTED
        # by the capacity clip was, until now, a silent undiagnosed death —
        # latch it per node (sticky; check_cap_ov is the loud-fail guard).
        cap_hit = (mask & (i == li)) & ~has_room
        if compact:
            cap_hit = cap_hit & (i >= b_n)
        cur_cap = col("cap_ov", n)
        setcol("cap_ov", n, cap_hit, cur_cap | jnp.ones_like(cur_cap))
        wr = app | ovw
        slot = jnp.where(app, pl, i)  # logical POSITION (== slot when off)
        if batched_logs and defer["on"]:
            # Phases 0/5: record only; applied at end of tick as one
            # duplicate-resolved scatter per node (reads in between go
            # through patch()). Masked lanes get row C — dropped by the
            # scatter, never matched by patch (read rows are < C).
            row_eff = jnp.where(wr, (ring(slot) if compact
                                     else jnp.clip(slot, 0, C - 1)), C)
            pending[n].append((row_eff, term_v, cmd_v, wr))
            if use_fc:
                slot32 = slot.astype(_I32)
                li32, i32 = li.astype(_I32), i.astype(_I32)
                # app implies i == li, so the post-write last_index is i + 1
                # in BOTH branches — no select, and no li on the chain.
                li_new = i32 + 1
                fc_patch_write(n, wr, slot32, term_v, cmd_v)
                # Live lastLogTerm maintenance (§3): the new cache row is
                # li_new - 1. app writes slot phys_len: the GHOST case
                # (phys_len != li) leaves the row at its STALE physical
                # content = the top window's base row (log[li]); otherwise
                # the row was just written. ovw writes row i = li_new - 1.
                W_T = deep_cache.W_TOP
                tw = (n - 1) * W_T
                ghost = wr & app & (slot32 != li32)
                fc_ov["v"] = fc_ov["v"] | (ghost & ~fcl["ok_topw"][tw])
                lt_new = jnp.where(ghost, fcl["f_topw"][tw], rt(term_v))
                s["last_term"] = _set_row(
                    s["last_term"], n - 1,
                    jnp.where(wr, lt_new, col("last_term", n)))
                # Realign the top window to base li_new: app shifts it down
                # one (its top slot becomes unknown until the next refill);
                # ovw (truncation) moves the base backward arbitrarily —
                # invalidate. Then overlay THIS write where it lands inside
                # the new window; rows >= C read as 0.
                old_w = [fcl["f_topw"][tw + j] for j in range(W_T)]
                old_ok = [fcl["ok_topw"][tw + j] for j in range(W_T)]
                for j in range(W_T):
                    if j + 1 < W_T:
                        sh_v, sh_ok = old_w[j + 1], old_ok[j + 1]
                    else:
                        sh_v = jnp.zeros((G,), _I32)
                        sh_ok = jnp.zeros((G,), dtype=bool)
                    v = jnp.where(app, sh_v, 0)
                    ok = app & sh_ok
                    row_j = li_new + j
                    hit = slot32 == row_j
                    oob = row_j >= C
                    v = jnp.where(oob, 0, jnp.where(hit, rt(term_v), v))
                    ok = ok | hit | oob
                    fcl["f_topw"][tw + j] = jnp.where(wr, v, old_w[j])
                    fcl["ok_topw"][tw + j] = jnp.where(wr, ok, old_ok[j])
                setcol("last_index", n, wr, i + 1)  # app => i == li: both branches = i+1
                setcol("phys_len", n, app, pl + 1)
                return wr, slot32
            setcol("last_index", n, wr, i + 1)  # app => i == li: both branches = i+1
            setcol("phys_len", n, app, pl + 1)
            return None
        ldt = s["log_term"].dtype  # narrow at write (cfg.log_dtype)
        # §15: the write slot is the ring translate of the position (the
        # clip below keeps masked-out lanes' garbage rows in range).
        w_slot = (jnp.clip(ring(slot), 0, C - 1) if compact
                  else jnp.clip(slot, 0, C - 1))
        if flags.dyn_log and not use_slices:
            # Flat masked read-modify-write of one global row per lane.
            rows = ((n - 1) * C + w_slot)[None, :]
            for name, v in (("log_term", term_v), ("log_cmd", cmd_v)):
                cur = jnp.take_along_axis(s[name], rows, axis=0)
                new = jnp.where(wr[None, :], v.astype(ldt)[None, :], cur)
                s[name] = jnp.put_along_axis(
                    s[name], rows, new, axis=0, inplace=False)
        elif flags.dyn_log:
            # Masked read-modify-write of one slot per lane (scatter form).
            rows = w_slot[None, :]
            for store, v in ((lt, term_v), (lc, cmd_v)):
                cur = jnp.take_along_axis(store[n - 1], rows, axis=0)
                new = jnp.where(wr[None, :], v.astype(ldt)[None, :], cur)
                store[n - 1] = jnp.put_along_axis(
                    store[n - 1], rows, new, axis=0, inplace=False)
        else:
            # One-hot masked write over the (C, G) log (Mosaic-compatible
            # form); term and cmd share the mask.
            oh = (logrow_c == (ring(slot) if compact
                               else slot)[None, :]) & wr[None, :]
            lt[n - 1] = jnp.where(oh, term_v.astype(ldt)[None, :], lt[n - 1])
            lc[n - 1] = jnp.where(oh, cmd_v.astype(ldt)[None, :], lc[n - 1])
        setcol("last_index", n, wr, i + 1)  # app => i == li: both branches = i+1
        setcol("phys_len", n, app, pl + 1)

    # Election-timer resets (SEMANTICS.md §7): each reset consumes one counted draw
    # and leaves el_left at the LAST consumed draw's value. In phases 2-5 nothing
    # reads el_left (phase 1 is its only reader), so those draws are DEFERRED:
    # resets just advance t_ctr and mark the node dirty; the caller materializes
    # el_left afterwards — identical bits, ~50x fewer threefry evaluations per tick.
    # Phase-F restarts must reset immediately (phase 1 reads them this same tick);
    # their draw (at pre-tick t_ctr, which phase F consumes first) is aux.el_draw_f.
    # (Constant built by comparison, not a dense bool literal — Mosaic-safe.)
    #
    # Chain shortening (ISSUE 4): the PER-EXCHANGE resets of phases 3/5 are
    # deferred a second time — nothing between an exchange and the end of the
    # tick reads el_armed, t_ctr, or the dirty mask (el_armed/el_left: phase 1
    # only; t_ctr: the caller's materialization), and every deferred update is
    # a boolean or / integer count — associative and commutative. So the
    # exchanges just APPEND their masks here, and one balanced tree-reduce at
    # tick end applies them: the old serial or/add chains (~2 ops per exchange
    # woven through the pair loops' critical path) collapse to log depth off
    # the path. Grid-phase resets (F, 2, 4) stay inline: they are two grid
    # ops each and phase 1 reads phase F's.
    aux_dirty = {"m": jnp.zeros((N, G), dtype=_I32) > 0}
    deferred_resets: dict = {n: [] for n in range(1, N + 1)}

    def reset_el_timer_col(n, mask):
        deferred_resets[n].append(mask)

    def flush_resets():
        """Apply the phases-3/5 deferred timer resets: per node, ONE balanced
        count of its reset masks (reset count = t_ctr advance; count > 0 =
        armed/dirty). Runs on the GRID form (callers flush after exit_cols),
        including at the cut-truncated early returns so the by-phase depth
        attribution sees the same program shape as a real tick."""
        if not any(deferred_resets.values()):
            return
        cnts = []
        for n in range(1, N + 1):
            ms = deferred_resets[n]
            cnts.append(_tree_reduce(
                jnp.add, [m.astype(_I32) for m in ms]) if ms
                else jnp.zeros((G,), _I32))
            deferred_resets[n] = []
        cnt_g = jnp.stack(cnts)
        hit = cnt_g != 0
        s["el_armed"] = s["el_armed"] | hit
        s["t_ctr"] = s["t_ctr"] + cnt_g.astype(s["t_ctr"].dtype)
        aux_dirty["m"] = aux_dirty["m"] | hit

    def reset_el_timer_grid(mask):
        s["el_armed"] = s["el_armed"] | mask
        s["t_ctr"] = s["t_ctr"] + mask.astype(_I32)
        aux_dirty["m"] = aux_dirty["m"] | mask

    # -- phase F: fault events (SEMANTICS.md §9) ----------------------------

    if flags.faults:
        crash_ev = s["up"] & aux["crash_m"]
        restart_ev = ~s["up"] & aux["restart_m"]
        s["up"] = (s["up"] & ~crash_ev) | restart_ev
        rst = restart_ev
        s["term"] = jnp.where(rst, 0, s["term"])
        s["voted_for"] = jnp.where(rst, -1, s["voted_for"])
        s["role"] = jnp.where(rst, FOLLOWER, s["role"])
        s["commit"] = jnp.where(rst, 0, s["commit"])
        s["last_index"] = jnp.where(rst, 0, s["last_index"])
        s["phys_len"] = jnp.where(rst, 0, s["phys_len"])
        s["round_state"] = jnp.where(rst, IDLE, s["round_state"])
        for f in (("round_left", "round_age", "bo_left", "last_term") if pc
                  else ("votes", "responses", "round_left", "round_age",
                        "bo_left", "last_term")):
            s[f] = jnp.where(rst, 0, s[f])
        if pc:
            # §18: one select per packed word wipes the whole exchange set
            # (votes/responses are popcounts — popcount(0) = 0).
            s["responded_bits"] = jnp.where(rst, 0, s["responded_bits"])
            s["vote_bits"] = jnp.where(rst, 0, s["vote_bits"])
        # Pair grids are owned by their FIRST node index (candidate/leader).
        # Arithmetic selects: pair-shaped tensors never hold i1 (Mosaic limits).
        keep = 1 - _rep_rows(
            rst.astype(s["next_index"].dtype if pc
                       else s["responded"].dtype), N)
        if not pc:
            s["responded"] = s["responded"] * keep
        s["next_index"] = s["next_index"] * keep
        s["match_index"] = s["match_index"] * keep
        s["hb_armed"] = s["hb_armed"] & ~rst
        s["hb_left"] = jnp.where(rst, 0, s["hb_left"])
        if compact:
            # §15: the reference persists nothing (quirk l) — a restart
            # wipes the snapshot too (the node rejoins empty and catches
            # up via InstallSnapshot). cap_ov stays sticky: a diagnostic
            # latch, not protocol state.
            for k_sn in SNAPSHOT_FIELDS:
                s[k_sn] = jnp.where(rst, 0, s[k_sn])
        if flags.delay:
            # §10: restart clears the slots the node OWNS (its sent requests died
            # with the process); crash clears nothing (messages stay on the wire).
            rst_rep = _rep_rows(rst, N)
            s["vq_due"] = jnp.where(rst_rep, -1, s["vq_due"])
            s["aq_due"] = jnp.where(rst_rep, -1, s["aq_due"])
        # Immediate reset: el_draw_f is the draw at pre-tick t_ctr, consumed here.
        s["el_left"] = jnp.where(rst, aux["el_draw_f"], s["el_left"])
        s["el_armed"] = s["el_armed"] | rst
        s["t_ctr"] = s["t_ctr"] + rst.astype(_I32)
        if use_fc:
            # Restart wipes the node's OWNED pair frontiers to 0: rows
            # -2/-1 are out of range and read as 0, so its pair caches
            # become 0/valid. Its PHYSICAL log is untouched (§3 logical
            # wipe), so caches where it is the PEER stay correct; f_top's
            # row moves to last_index = 0, whose stale content is unknown.
            # The mailbox second-entry window (row ni = 0) is IN range and
            # may hold stale physical content — invalidated, not zeroed
            # (refilled on demand after the node's next win-jump anyway).
            for a in range(1, N + 1):
                ra = rst[a - 1]
                for b in range(1, N + 1):
                    pi = (a - 1) * N + (b - 1)
                    for k in fc_pvals:
                        okk = deep_cache.ok_name(k)
                        fcl[k][pi] = jnp.where(ra, 0, fcl[k][pi])
                        if k in deep_cache.PAIR_VALS_MB:
                            fcl[okk][pi] = fcl[okk][pi] & ~ra
                        else:
                            fcl[okk][pi] = fcl[okk][pi] | ra
                for j in range(deep_cache.W_TOP):
                    tw = (a - 1) * deep_cache.W_TOP + j
                    fcl["ok_topw"][tw] = fcl["ok_topw"][tw] & ~ra
    if flags.links:
        lu = s["link_up"]
        s["link_up"] = lu * (1 - aux["link_fail"]) + (1 - lu) * aux["link_heal"]

    # Effective edge health (§9): iid survival ∧ link health ∧ both ends up.
    # HOISTED (ISSUE 4): up/link_up/edge_iid are all fixed after phase F, so
    # the N^2 directed-pair masks compute ONCE here — one independent wave
    # the scheduler can issue ahead of the serial pair loops — instead of
    # being rebuilt at every exchange call site. Balanced (A∧B)∧(C∧D)
    # association; still rank-2 only, no (N, N, G) mask is ever built.
    up = s["up"]
    _eok = {}
    for _a in range(1, N + 1):
        for _b in range(1, N + 1):
            _eok[(_a, _b)] = (
                ((aux["edge_iid"][pair(_a, _b)] != 0)
                 & (s["link_up"][pair(_a, _b)] != 0))
                & (up[_a - 1] & up[_b - 1]))

    def edge_ok(a, b):
        return _eok[(a, b)]

    if batched_logs:
        # Deferral starts HERE (post-phase-F, so restart wipes are already
        # applied): phase-0 adds join the same pending list (chronological),
        # so consume-time patch() and the final resolved scatter replay
        # phase 0 + phase 5 in canonical order from the pre-tick stored log.
        defer["on"] = True

    if use_fc and (flags.periodic or flags.inject):
        # EARLY top-window refill: a phase-0 GHOST append (post-truncation
        # cmd_node) consumes f_topw BEFORE the main phase-5 refill runs —
        # e.g. the tick right after a phase-5 truncation invalidated the
        # window. Top the windows up here, but only on ticks that actually
        # inject commands (lax.cond on the aux masks — the take is real
        # work, and cmd ticks are 1-in-cmd_period).
        W_T = deep_cache.W_TOP
        due = jnp.zeros((), dtype=bool)
        if flags.periodic:
            due = due | jnp.any(aux["periodic"][0] >= 0)
        if flags.inject:
            due = due | jnp.any(aux["inject"] >= 0)
        ew_rows, ew_ok, ew_v = [], [], []
        need_any = jnp.zeros((), dtype=bool)
        for n in range(1, N + 1):
            li_e = col("last_index", n).astype(_I32)
            # Only GHOST-STATE nodes (phys > li) can consume the window —
            # see the main-refill gate note — so only they wake the cond.
            ghosty_e = col("phys_len", n).astype(_I32) > li_e
            for j in range(W_T):
                tw = (n - 1) * W_T + j
                r = li_e + j
                ew_rows.append((n - 1) * C + jnp.clip(r, 0, C - 1))
                ew_ok.append(fcl["ok_topw"][tw] | ~((r >= 0) & (r < C)))
                ew_v.append(r)
                need_any = need_any | (~ew_ok[-1] & ghosty_e).any()
        # Fire on command ticks (the consumer) AND only when some window
        # row is actually missing for a node that could consume it.
        due = due & need_any

        def _early_refill(_):
            vals = jnp.take_along_axis(
                s["log_term"], jnp.stack(ew_rows), axis=0).astype(_I32)
            out_v, out_ok = [], []
            for k in range(N * W_T):
                need = ~ew_ok[k]
                inr_k = (ew_v[k] >= 0) & (ew_v[k] < C)
                v = jnp.where(inr_k, vals[k], 0)
                # Out-of-range window rows STORE 0 instead of retaining the
                # stale cached value they are about to be marked valid over
                # — the bound()/oob convention every other refill path keeps
                # (ADVICE r5 finding 1; rows outside [0, C) read as 0).
                out_v.append(jnp.where(need | ~inr_k, v, fcl["f_topw"][k]))
                out_ok.append(jnp.ones_like(fcl["ok_topw"][k]))
            return jnp.stack(out_v), jnp.stack(out_ok)

        def _early_skip(_):
            return (jnp.stack([fcl["f_topw"][k] for k in range(N * W_T)]),
                    jnp.stack([fcl["ok_topw"][k] for k in range(N * W_T)]))

        ev, eo = lax.cond(due, _early_refill, _early_skip, None)
        for k in range(N * W_T):
            fcl["f_topw"][k] = ev[k]
            fcl["ok_topw"][k] = eo[k]

    # -- phase 0: command injection (quirk k) -------------------------------

    if flags.periodic:
        n = cfg.cmd_node
        cmd = aux["periodic"][0]
        log_add(n, col("last_index", n), col("term", n), cmd,
                (cmd >= 0) & col("up", n))
    if flags.inject:
        for n in range(1, N + 1):
            cmd = aux["inject"][n - 1]
            log_add(n, col("last_index", n), col("term", n), cmd,
                    (cmd >= 0) & col("up", n))
    if (flags.periodic or flags.inject) and not use_fc:
        # Refresh the lastLogTerm cache for nodes phase 0 may have appended
        # to: phase 3 reads state.last_term this same tick, and a ghost
        # append (§3) makes the post-append value a LOG read (slot li-1),
        # not the written term. In batched mode the add was deferred, so the
        # raw gather is patched with this node's pending writes. (fcache
        # mode maintains last_term LIVE inside log_add — the ghost value
        # comes from f_top — so no gather is needed here at all.)
        p0_nodes = set([cfg.cmd_node] if flags.periodic else [])
        if flags.inject:
            p0_nodes.update(range(1, N + 1))
        for n in sorted(p0_nodes):
            li_n = col("last_index", n)
            raw = log_gather("log_term", n, li_n - 1)
            if batched_logs:
                prow_lt = (ring(jnp.maximum(li_n.astype(_I32) - 1, 0))
                           if compact else jnp.clip(li_n - 1, 0, C - 1))
                raw = patch("log_term", n, prow_lt, raw)
            if compact:
                # §15 boundary: a fully folded log's lastLogTerm is the
                # snapshot term (position base - 1).
                raw = jnp.where(li_n == col("snap_index", n),
                                col("snap_term", n).astype(_I32), raw)
            s["last_term"] = _set_row(
                s["last_term"], n - 1, jnp.where(li_n >= 1, raw, 0))

    if cut < 1:
        _ps.close()
        return aux_dirty["m"]
    # -- phase 1: timers (independent countdowns) ---------------------------
    _ps.enter("p1")

    armed = s["el_armed"] & up
    left = s["el_left"] - armed.astype(s["el_left"].dtype)
    fire = armed & (left <= 0)
    s["el_left"] = left
    s["el_armed"] = s["el_armed"] & ~fire
    s["role"] = jnp.where(fire, CANDIDATE, s["role"])
    start_round = fire

    in_bo = (s["round_state"] == BACKOFF) & up
    bleft = s["bo_left"] - in_bo.astype(s["bo_left"].dtype)
    bfire = in_bo & (bleft <= 0)
    s["bo_left"] = bleft
    s["round_state"] = jnp.where(bfire, IDLE, s["round_state"])
    start_round = start_round | bfire

    if cut < 2:
        _ps.close()
        return aux_dirty["m"]
    # -- phase 2: round starts ---------------------------------------------
    _ps.enter("p2")

    is_cand = s["role"] == CANDIDATE
    init = start_round & is_cand
    node_ids = jax.lax.broadcasted_iota(s["voted_for"].dtype, (N, G), 0) + 1
    s["term"] = s["term"] + init.astype(_I32)
    s["voted_for"] = jnp.where(init, node_ids, s["voted_for"])
    if pc:
        # §18: round start clears the packed exchange words — the wide
        # votes/responses/responded resets in one select each.
        s["responded_bits"] = jnp.where(init, 0, s["responded_bits"])
        s["vote_bits"] = jnp.where(init, 0, s["vote_bits"])
    else:
        s["votes"] = jnp.where(init, 0, s["votes"])
        s["responses"] = jnp.where(init, 0, s["responses"])
        s["responded"] = s["responded"] * (
            1 - _rep_rows(init.astype(s["responded"].dtype), N))
    s["round_left"] = jnp.where(init, cfg.round_ticks, s["round_left"])
    s["round_age"] = jnp.where(init, 0, s["round_age"])
    s["round_state"] = jnp.where(init, ACTIVE, s["round_state"])
    s["rounds"] = s["rounds"] + init.astype(_I32)
    demoted_bo = start_round & ~is_cand
    s["round_state"] = jnp.where(demoted_bo, IDLE, s["round_state"])
    reset_el_timer_grid(demoted_bo)

    if cut < 3:
        _ps.close()
        return aux_dirty["m"]
    # -- phase 3: vote exchanges --------------------------------------------
    _ps.enter("p3")

    # Hoisted per-node last-log position/term: INVARIANT across phase 3 (no
    # vote path touches logs or last_index), so the N*N pairs share N reads
    # instead of recomputing one per pair. llt_h comes from the state-carried
    # lastLogTerm cache (state.last_term — zeroed by restart in phase F,
    # refreshed after phase-0 appends above, recomputed from the final log at
    # tick end), so phase 3 issues NO log gathers at all; llt_h[n-1] is 0
    # when the log is empty, which is exactly the request convention
    # (lastLogTerm 0 on an empty log) AND the handler's up-to-dateness input
    # (rej_* are guarded by p_li >= 1).
    if use_columnar:
        enter_cols()  # phase 3 runs on the columnar view
    lli_h = [col("last_index", n) for n in range(1, N + 1)]
    llt_h = [col("last_term", n) for n in range(1, N + 1)]
    # Deferred phase-3 tally/demote masks (see vote_exchange): per node,
    # applied as one balanced tree-reduce after the pair loops.
    p3_resp = {n: [] for n in range(1, N + 1)}
    p3_vote = {n: [] for n in range(1, N + 1)}
    p3_dem = {n: [] for n in range(1, N + 1)}
    if flags.delay:
        # §10 due-scan hoist (ISSUE 4): a pair's in-flight slot is written
        # only by its OWN send/delivery, and each pair's first delivery scan
        # precedes its send — so all N^2 due tests read pre-phase values and
        # issue as one independent wave ahead of the serial pair loops. τ=0
        # second deliveries re-test the just-sent slot live.
        vdue0 = {(c, p): prow("vq_due", c, p) == 0
                 for c in range(1, N + 1) for p in range(1, N + 1)}

    def delay_for(a, b):
        # §10 per-pair send delay this tick (static constant when lo == hi).
        if cfg.delay_lo == cfg.delay_hi:
            return jnp.full((G,), cfg.delay_lo, dtype=prow("vq_due", a, b).dtype)
        return aux["delay"][pair(a, b)]

    def put_pair(name, a, b, mask, vals):
        set_prow(name, a, b, jnp.where(mask, vals, prow(name, a, b)))

    def vote_exchange(c, p, att, req_term, req_lli, req_llt, guard):
        """§6.1 handler on p + candidate tally, masked by `att`; the request fields
        are (G,) snapshots (live reads on the synchronous path, §10 slot contents on
        the mailbox path). `guard` additionally masks the CANDIDATE-side processing
        (the §10 straggler rule); the handler mutation on p is governed by `att`
        alone."""
        p_term = col("term", p)
        p_vf = col("voted_for", p)
        p_li = lli_h[p - 1]
        p_llt = llt_h[p - 1]
        rej_stale = (p_li >= 1) & (req_llt < p_llt)
        rej_short = (p_li >= 1) & (req_llt == p_llt) & (req_lli < p_li)
        # The rej_* legs read only hoisted log snapshots and request fields
        # — OFF the term chain — so they pre-combine and the live term
        # compare joins them in ONE op (the term cells are the phase-3
        # serial spine; the old left fold put two serial ands on it).
        grant_gt = (req_term > p_term) & ~(rej_stale | rej_short)
        # Boolean algebra, not where-of-bools (Mosaic i1-select limits):
        # term < p.term -> False; == -> votedFor check (quirk g); > -> log check.
        granted = ((req_term == p_term) & (p_vf == c)) | grant_gt
        adopt = att & grant_gt
        setcol("term", p, adopt, req_term)
        setcol("voted_for", p, adopt, c)
        p3_dem[p].append(adopt)  # role write deferred (same FOLLOWER const)
        reset_el_timer_col(p, adopt)
        resp_term = col("term", p)
        # Candidate tally (RaftServer.kt:209-211). resp_term is compared against
        # c's LIVE term (RaftServer.kt:210 reads currentTerm at response
        # processing); within one tick c's term cannot change during its own peer
        # loop, so this is bit-identical to comparing against the request term on
        # the synchronous path.
        #
        # The tally WRITES are deferred (ISSUE 4 chain shortening): nothing
        # in phase 3 reads votes/responses/role (phase 4 is their first
        # reader), every vote increment commutes, and every phase-3 role
        # write stores the same FOLLOWER constant — so the per-exchange
        # serial +1/or chains collapse to one balanced tree-reduce per node
        # after the pair loops. Masks are still built HERE, from live state
        # (the quirk-f compare reads c's term at this point in the order).
        tal = att & guard
        if pc:
            # §18: the responded write is ONE inline OR of bit p-1 into
            # c's packed word — the send guard and the τ=0 redelivery
            # scan read it through responded_clear, so the in-loop
            # ordering matches the wide put_pair exactly. No deferred
            # response tally exists at all (responses ==
            # popcount(responded_bits) at every phase boundary); the
            # grant joins p3_vote as a pre-shifted bit for the balanced
            # OR after the pair loops (each pair fires at most once per
            # round — the send guard — so OR == the wide add on the
            # popcount).
            orcol("responded_bits", c, tal.astype(_I32) << (p - 1))
        else:
            put_pair("responded", c, p, tal, 1)
            p3_resp[c].append(tal)
        p3_dem[c].append(tal & (resp_term > col("term", c)))  # quirk f
        p3_vote[c].append((tal & granted).astype(_I32) << (p - 1) if pc
                          else (tal & granted))

    def vote_deliver(c, p, due=None):
        # §10 delivery: response leg evaluated at the delivery tick; either-end
        # failure voids the whole exchange. Candidate processing additionally
        # guarded by the round stamp (straggler cancellation). `due` may be
        # supplied pre-hoisted (vdue0 — the first scan per pair); None =
        # live read (the τ=0 same-iteration redelivery).
        if due is None:
            due = prow("vq_due", c, p) == 0
        att = due & edge_ok(p, c)
        guard = (col("round_state", c) == ACTIVE) & (
            prow("vq_round", c, p) == col("rounds", c)
        )
        req_term = prow("vq_term", c, p)
        req_lli, req_llt = prow("vq_lli", c, p), prow("vq_llt", c, p)
        put_pair("vq_due", c, p, due, jnp.full((G,), -1, dtype=s["vq_due"].dtype))
        vote_exchange(c, p, att, req_term, req_lli, req_llt, guard)

    for c in range(1, N + 1):
        c_attempting = (col("round_state", c) == ACTIVE) & (
            col("round_age", c) % cfg.retry_ticks == 0
        )
        for p in range(1, N + 1):
            if flags.delay:
                # In-flight slots from earlier ticks (hoisted due scan).
                vote_deliver(c, p, due=vdue0[(c, p)])
                # Balanced join; responded (just written by this pair's own
                # delivery above) is the deep input and joins last.
                att = (
                    (c_attempting & edge_ok(c, p))  # request leg at send
                    & responded_clear(c, p)
                )
                put_pair("vq_term", c, p, att, col("term", c))
                put_pair("vq_lli", c, p, att, lli_h[c - 1])
                put_pair("vq_llt", c, p, att, llt_h[c - 1])
                put_pair("vq_round", c, p, att, col("rounds", c))
                put_pair("vq_due", c, p, att, delay_for(c, p))
                if cfg.delay_lo == 0:
                    vote_deliver(c, p)  # τ=0: the just-sent slot, same iteration
            else:
                att = (
                    (c_attempting & responded_clear(c, p))
                    & (edge_ok(c, p) & edge_ok(p, c))
                )
                # Request built from c's live state (RaftServer.kt:200-207);
                # the log fields come from the hoisted per-node snapshot
                # (invariant in phase 3).
                true_g = jnp.ones((G,), dtype=bool)
                vote_exchange(c, p, att, col("term", c),
                              lli_h[c - 1], llt_h[c - 1], true_g)

    # Apply the deferred phase-3 tallies/demotes: one balanced reduce per
    # node (integer adds and same-constant role writes commute — any
    # association/order yields the same bits as the old in-loop chains).
    for n2 in range(1, N + 1):
        if p3_dem[n2]:
            setcol("role", n2, _tree_reduce(jnp.logical_or, p3_dem[n2]),
                   FOLLOWER)
        if pc:
            # §18: the vote tally is a balanced OR of this node's pre-
            # shifted grant bits (distinct bits — each pair fires at most
            # once per round — so bitwise_or is associative/commutative
            # AND exact against the wide add).
            if p3_vote[n2]:
                orcol("vote_bits", n2,
                      _tree_reduce(jnp.bitwise_or, p3_vote[n2]))
            continue
        for field, ms in (("responses", p3_resp[n2]), ("votes", p3_vote[n2])):
            if not ms:
                continue
            cur = col(field, n2)
            inc = _tree_reduce(jnp.add, [m.astype(cur.dtype) for m in ms])
            if view:
                view[field][n2 - 1] = cur + inc
            else:
                s[field] = _set_row(s[field], n2 - 1, cur + inc)

    # -- phase 4: round conclusions -----------------------------------------

    if use_columnar:
        exit_cols()  # phase 4 is grid-wide
    if cut < 4:
        flush_resets()
        _ps.close()
        return aux_dirty["m"]
    _ps.enter("p4")
    act = (s["round_state"] == ACTIVE) & up
    if pc:
        # §18 quorum compare: one popcount per packed word replaces the
        # N-way tallies (responses/votes ARE the popcounts of the
        # exchange words — the invariant the packed domain rests on).
        resp_n = popcount32(s["responded_bits"].astype(_I32))
        vote_n = popcount32(s["vote_bits"].astype(_I32))
    else:
        resp_n, vote_n = s["responses"], s["votes"]
    concl = act & ((resp_n >= maj) | (s["round_left"] <= 0))
    is_cand = s["role"] == CANDIDATE
    win = concl & is_cand & (vote_n >= maj)
    lose = concl & is_cand & ~win
    dem = concl & ~is_cand
    s["role"] = jnp.where(win, LEADER, s["role"])
    win_rep = _rep_rows(win.astype(s["next_index"].dtype), N)
    s["next_index"] = (
        win_rep * _rep_rows(s["commit"] + 1, N) + (1 - win_rep) * s["next_index"]
    )  # quirk b
    s["match_index"] = (1 - win_rep) * s["match_index"]
    s["hb_armed"] = s["hb_armed"] | win
    s["hb_left"] = jnp.where(win, 0, s["hb_left"])  # initial delay 0
    if use_fc:
        # quirk-b jump: the winner's pair frontiers move to commit + 1 —
        # every cached frontier value of its owned pairs becomes unknown
        # (the refill below serves the ones phase 5 consumes this tick).
        for a in range(1, N + 1):
            wa = win[a - 1]
            for b in range(1, N + 1):
                pi = (a - 1) * N + (b - 1)
                for k in fc_pvals:
                    okk = deep_cache.ok_name(k)
                    fcl[okk][pi] = fcl[okk][pi] & ~wa
    s["round_state"] = jnp.where(win | dem, IDLE, s["round_state"])
    s["round_state"] = jnp.where(lose, BACKOFF, s["round_state"])
    s["bo_left"] = jnp.where(lose, aux["bdraw"], s["bo_left"])
    s["b_ctr"] = s["b_ctr"] + lose.astype(_I32)
    reset_el_timer_grid(dem)
    ongoing = act & ~concl
    s["round_left"] = s["round_left"] - ongoing.astype(s["round_left"].dtype)
    s["round_age"] = s["round_age"] + ongoing.astype(s["round_age"].dtype)

    if cut < 5:
        flush_resets()
        _ps.close()
        return aux_dirty["m"]
    # -- phase 5: append / heartbeat ----------------------------------------
    _ps.enter("p5")

    def append_exchange(l, p, act5, req_term, req_commit, pli, plt,
                        has_entry, ent_t, ent_c, p_plt=None):
        """§6.2 handler on p + leader response processing, masked by `act5`; the
        request fields are (G,) snapshots (live reads on the synchronous path,
        §10 slot contents on the mailbox path). Leader-side processing always
        reads l's LIVE state (RaftServer.kt:146-168 — no latch for appends).
        `p_plt` (p's log term at pli) may be supplied pre-gathered (the
        batched deep-log engine); None = gather here."""
        p_term = col("term", p)
        if p != l:
            adopt = act5 & (req_term > p_term)
            setcol("term", p, adopt, req_term)
            setcol("voted_for", p, adopt, -1)
            # quirk d: ANY foreign append demotes — adopt ⊆ act5 and both
            # stores are the same FOLLOWER constant, so the single act5
            # write covers the adopt one (one select on the role chain).
            setcol("role", p, act5, FOLLOWER)
            reset_el_timer_col(p, adopt)
            reset_el_timer_col(p, act5)
        p_li = col("last_index", p)
        p_commit = col("commit", p)
        cadv = act5 & (req_commit > p_commit)
        setcol("commit", p, cadv, jnp.minimum(req_commit, p_li))  # quirk e
        if p_plt is None:
            p_plt = log_gather("log_term", p, pli)
        if compact:
            # §15: p's snapshot covers positions below its base — the
            # boundary row base-1 checks against snap_term, rows below it
            # are ABSORBED (folded ⇒ committed ⇒ matching by the committed-
            # prefix guarantee; a quirk run that violated it has already
            # latched the monitor).
            b_p = col("snap_index", p).astype(_I32)
            p_plt = jnp.where((pli >= 0) & (pli == b_p - 1),
                              col("snap_term", p).astype(_I32), p_plt)
            below = (pli >= 0) & (pli < b_p - 1)
            succ = ((pli == -1) | below
                    | ((p_li > pli) & (pli >= 0) & (p_plt == plt)))
        else:
            succ = (pli == -1) | ((p_li > pli) & (pli >= 0) & (p_plt == plt))
        add_info = log_add(p, pli + 1, ent_t, ent_c,
                           (act5 & has_entry) & succ)
        resp_term = col("term", p)
        # --- leader processes the response (RaftServer.kt:146-168) ---
        if p != l:
            l_term = col("term", l)
            demote = act5 & (resp_term > l_term)
            setcol("term", l, demote, resp_term)
            setcol("role", l, demote, FOLLOWER)
            reset_el_timer_col(l, demote)
        else:
            demote = jnp.zeros((G,), dtype=_I32) > 0
        proc = act5 & ~demote & succ
        with_e = proc & has_entry
        nfail = act5 & ~demote & ~succ
        ni = prow("next_index", l, p)
        # Arithmetic update instead of the two-deep select cascade: with_e
        # and nfail are disjoint, so ni + (with_e - nfail) takes the same
        # value in every branch while the delta computes OFF ni's chain —
        # the next_index cell advances one op per exchange, not two.
        d_ni = with_e.astype(ni.dtype) - nfail.astype(ni.dtype)
        set_prow("next_index", l, p, ni + d_ni)
        mi = prow("match_index", l, p)
        set_prow("match_index", l, p,
                 jnp.where(with_e, mi + 1,
                           jnp.where(proc & ~has_entry, pli + 1, mi)))
        # Commit advancement (quirk a), evaluated per response — in ORDER-
        # STATISTIC form (ISSUE 4): count(mi > commit) >= maj is exactly
        # maj-th-largest(mi) > commit for integers, and the selection
        # network reads ONLY the match_index rows, so the leader's commit
        # chain grows by one compare + one select per exchange instead of
        # carrying the whole accumulate-and-count tally (the old form put
        # ~N+3 serial ops on the commit cell per exchange — the deepest
        # recurring segment of the phase-5 critical path).
        # The network runs on the PRE-update row for q == p, bumped +1
        # unconditionally ("pretend" row): the commit write is masked by
        # with_e, and exactly there the true post-update row IS mi + 1 — so
        # the selection depends only on the (older) match_index rows and
        # issues OFF the exchange's with_e/succ frontier; where ~with_e the
        # pretend value is never consumed (the write is masked out).
        l_commit = col("commit", l)
        m_maj = _kth_largest(
            [prow("match_index", l, q) if q != p else mi + 1
             for q in range(1, N + 1)], maj)
        setcol("commit", l, with_e & (m_maj > l_commit), l_commit + 1)
        if use_fc and defer["on"]:
            # Frontier-cache shift (ops/deep_cache.py): the exchange moved
            # next_index by +1 (with_e) or -1 (nfail); re-point the cached
            # rows. All olds are read BEFORE any assignment.
            pi_lp = pair(l, p)
            wr_p, slot_p = add_info
            i32o = ni.astype(_I32)  # pre-update next_index (= pli + 2)
            o = {k: fcl[k][pi_lp] for k in
                 (("f_pli", "f_ent_t", "f_ent_c", "f_ppli",
                   "ok_pli", "ok_ent_t", "ok_ent_c", "ok_ppli")
                  + (("f_ent2_t", "f_ent2_c", "ok_ent2_t", "ok_ent2_c")
                     if flags.delay else ()))}
            zero = jnp.zeros((G,), _I32)
            no = jnp.zeros((G,), dtype=bool)
            # with_e: pli' = old entry row; entry row i is unknown until
            # the next write lands there; ppli' (row i-1 of p) is the value
            # this exchange just wrote — unless the write was a §3 ghost
            # (slot != i-1), which leaves the stale row unknown here (the
            # refill serves it on next consume; rare).
            wrote_im1 = wr_p & (slot_p == i32o - 1)
            ent_w = rt(ent_t)

            def upd(key, adv_v, adv_ok, rec_v, rec_ok):
                okk = "ok_" + key[2:]
                fcl[key][pi_lp] = jnp.where(
                    with_e, adv_v, jnp.where(nfail, rec_v, o[key]))
                fcl[okk][pi_lp] = jnp.where(
                    with_e, adv_ok, jnp.where(nfail, rec_ok, o[okk]))

            upd("f_pli", o["f_ent_t"], o["ok_ent_t"], zero, no)
            if flags.delay:
                # Known-delivery regime: the second-entry window rotates
                # through the entry slot, so a same-tick advance+send
                # consumes a VALID entry row (the whole point of
                # PAIR_VALS_MB); recede shifts run the other way. The
                # receded entry-cmd row (ni - 2's cmd) has no cache source
                # — unknown, served by the refill on next consume.
                upd("f_ent_t", o["f_ent2_t"], o["ok_ent2_t"],
                    o["f_pli"], o["ok_pli"])
                upd("f_ent_c", o["f_ent2_c"], o["ok_ent2_c"], zero, no)
                upd("f_ent2_t", zero, no, o["f_ent_t"], o["ok_ent_t"])
                upd("f_ent2_c", zero, no, o["f_ent_c"], o["ok_ent_c"])
            else:
                upd("f_ent_t", zero, no, o["f_pli"], o["ok_pli"])
                upd("f_ent_c", zero, no, zero, no)
            upd("f_ppli", jnp.where(wrote_im1, ent_w, zero), wrote_im1,
                zero, no)

    def install_exchange(l, p, act, req_term, req_si, req_st, req_dg,
                         req_commit):
        """§15 InstallSnapshot handler on p + leader response processing,
        masked by `act`; request fields are (G,) snapshots (live reads on
        the synchronous path, §10 slot contents — aq_hase == 2 — on the
        mailbox path). Mirrors the §6.2 append shape: term adoption, the
        quirk-d foreign demote+reset, install iff req.snap_index >
        p.last_index (log window emptied onto the snapshot; ring slot
        CONTENTS untouched — stale bits stay bit-comparable across
        engines), the quirk-e commit advance, then the leader response:
        always success — next_index := snap_index + 1, match_index :=
        snap_index, with the quirk-a commit tally."""
        req_si = req_si.astype(_I32)
        req_st = req_st.astype(_I32)
        req_dg = req_dg.astype(_I32)
        p_term = col("term", p)
        if p != l:
            adopt = act & (req_term > p_term)
            setcol("term", p, adopt, req_term)
            setcol("voted_for", p, adopt, -1)
            setcol("role", p, act, FOLLOWER)  # quirk-d mirror
            reset_el_timer_col(p, adopt)
            reset_el_timer_col(p, act)
        p_li = col("last_index", p)
        do_inst = act & (req_si > p_li.astype(_I32))
        setcol("snap_index", p, do_inst, req_si)
        setcol("snap_term", p, do_inst, req_st)
        setcol("snap_digest", p, do_inst, req_dg)
        setcol("last_index", p, do_inst, req_si)
        setcol("phys_len", p, do_inst, req_si)
        setcol("commit", p, do_inst, req_si)
        setcol("last_term", p, do_inst, req_st)  # empty window: snap_term
        # quirk-e-flavor commit advance rides the message's leaderCommit.
        p_li2 = col("last_index", p)
        p_commit = col("commit", p)
        cadv = act & (req_commit > p_commit)
        setcol("commit", p, cadv, jnp.minimum(req_commit, p_li2))
        resp_term = col("term", p)
        if p != l:
            l_term = col("term", l)
            demote = act & (resp_term > l_term)
            setcol("term", l, demote, resp_term)
            setcol("role", l, demote, FOLLOWER)
            reset_el_timer_col(l, demote)
        else:
            demote = jnp.zeros((G,), dtype=_I32) > 0
        proc = act & ~demote
        ni = prow("next_index", l, p)
        set_prow("next_index", l, p,
                 jnp.where(proc, (req_si + 1).astype(ni.dtype), ni))
        mi = prow("match_index", l, p)
        set_prow("match_index", l, p,
                 jnp.where(proc, req_si.astype(mi.dtype), mi))
        # quirk-a tally on the "pretend" post-update rows (see
        # append_exchange's commit note — identical discipline).
        l_commit = col("commit", l)
        m_maj = _kth_largest(
            [prow("match_index", l, q) if q != p
             else jnp.where(proc, req_si.astype(mi.dtype), mi)
             for q in range(1, N + 1)], maj)
        setcol("commit", l, proc & (m_maj > l_commit), l_commit + 1)

    def append_deliver(l, p, p_plt=None, due=None):
        # §10 delivery: response leg at the delivery tick; either-end failure voids
        # the exchange. No straggler guard — append responses always process
        # against live leader state (the reference never cancels them).
        # `p_plt` may be supplied pre-gathered (the known-delivery batched /
        # frontier-cache engines); None = gather inside append_exchange.
        # `due` may be supplied pre-hoisted (adue0 — the first scan per
        # pair); None = live read (the τ=0 same-iteration redelivery).
        if due is None:
            due = prow("aq_due", l, p) == 0
        att = due & edge_ok(p, l)
        req = {k: prow(k, l, p) for k in
               ("aq_term", "aq_commit", "aq_pli", "aq_plt",
                "aq_hase", "aq_ent_t", "aq_ent_c")}
        put_pair("aq_due", l, p, due, jnp.full((G,), -1, dtype=s["aq_due"].dtype))
        if compact:
            # §15: slots with aq_hase == 2 are InstallSnapshot messages
            # (snap_index/snap_term/digest riding the pli/plt/ent_t seats).
            is_inst = req["aq_hase"] == 2
            append_exchange(l, p, att & ~is_inst, req["aq_term"],
                            req["aq_commit"], req["aq_pli"], req["aq_plt"],
                            req["aq_hase"] == 1, req["aq_ent_t"],
                            req["aq_ent_c"], p_plt=p_plt)
            install_exchange(l, p, att & is_inst, req["aq_term"],
                             req["aq_pli"], req["aq_plt"],
                             req["aq_ent_t"], req["aq_commit"])
        else:
            append_exchange(l, p, att, req["aq_term"], req["aq_commit"],
                            req["aq_pli"], req["aq_plt"],
                            req["aq_hase"] != 0,
                            req["aq_ent_t"], req["aq_ent_c"], p_plt=p_plt)

    if use_columnar:
        enter_cols()  # phase 5 runs on the columnar view

    if flags.delay:
        # Hoisted §10 due scan, phase-5 leg (same argument as vdue0: a
        # pair's slot is written only by its own send, which runs after its
        # delivery — all first-scan due tests are pre-phase values).
        adue0 = {(l, p): prow("aq_due", l, p) == 0
                 for l in range(1, N + 1) for p in range(1, N + 1)}

    if batched_logs:
        def bounded(idx, v, n=None):
            # log_gather's out-of-[0, C) => 0 convention for a raw take;
            # §15 (compact, with the owning node supplied): the node's
            # live-window test instead (same translate-or-latch map).
            if compact and n is not None:
                return jnp.where(_win_ok(n, idx), v, 0)
            return jnp.where((idx >= 0) & (idx < C), v, 0)

        def inr(r):
            return (r >= 0) & (r < C)

    fc_cons = {}
    if use_fc:
        # ----- frontier-cache refill (ops/deep_cache.py) -----
        # Demands: cache entries phase 5 will CONSUME this tick that are
        # invalid and in-range, ranked per lane over a static enumeration
        # and served by ONE budgeted take per log array. The consumption
        # masks mirror the loop-head logic exactly (fire/skip use only
        # state phase 5 itself reads before any exchange).
        i_all = {(a, b): prow("next_index", a, b)
                 for a in range(1, N + 1) for b in range(1, N + 1)}
        li32f = {n: col("last_index", n).astype(_I32) for n in range(1, N + 1)}
        fire_pre = {}
        for l in range(1, N + 1):
            armed_f = col("hb_armed", l) & col("up", l)
            fire_pre[l] = armed_f & ~(col("hb_left", l) > 0)
        # (gate, hard, target node, local row, cache key, cache row index);
        # consumption masks and demand entries built in ONE pass per pair
        # (r6 dead-op pruning: the masks' i32/he_f subterms are shared with
        # the entry gates instead of being rebuilt in a second loop).
        t_entries, c_entries = [], []
        for l in range(1, N + 1):
            for p in range(1, N + 1):
                pi = pair(l, p)
                i32 = i_all[(l, p)].astype(_I32)
                pli_f = i32 - 2
                he_f = li32f[l] >= i32
                skip_f = ((pli_f >= 0) & ~(pli_f < li32f[l])) \
                    | (he_f & (i32 <= 0))
                cns = fire_pre[l] & ~skip_f
                fc_cons[(l, p)] = cns
                t_entries.append((cns & ~fcl["ok_pli"][pi] & inr(i32 - 2),
                                  True, l, i32 - 2, "f_pli", pi))
                # Entry-row demands: the SYNC engine consumes ent only when
                # an entry exists (he_f); a MAILBOX send snapshots the
                # PHYSICAL row i-1 into the slot for every attempt — the
                # per-pair engine gathers it unconditionally, so heartbeat
                # sends need the value too (dead payload when aq_hase is 0,
                # but bit-visible slot state).
                ent_gate = cns if flags.delay else cns & he_f
                t_entries.append((ent_gate & ~fcl["ok_ent_t"][pi]
                                  & inr(i32 - 1), True, l, i32 - 1,
                                  "f_ent_t", pi))
                if flags.delay:
                    # Under the mailbox f_ppli is consumed by the DELIVERY
                    # leg (the handler's prevLog check at the slot's own
                    # aq_pli snapshot), not the send: demand it for due
                    # slots whose snapshot still sits at the live frontier
                    # (aq_pli == ni - 2; win-jumps/restarts break that and
                    # the consume-time guard raises OV instead of reading
                    # a row the cache cannot represent).
                    due_p = adue0[(l, p)]
                    dcons = (due_p & edge_ok(p, l)
                             & (prow("aq_pli", l, p).astype(_I32)
                                == i32 - 2)
                             & (li32f[p] > i32 - 2))
                    t_entries.append((dcons & ~fcl["ok_ppli"][pi]
                                      & inr(i32 - 2),
                                      True, p, i32 - 2, "f_ppli", pi))
                    # Second-entry window (PAIR_VALS_MB): consumed when a
                    # due delivery WITH AN ENTRY (the only shift source)
                    # advances the frontier and the SAME tick's send
                    # snapshots the new physical row i-1 — the with_e
                    # shift rotates f_ent2 into f_ent, so it must be valid
                    # by then (no he gate: physical rows, see ent_gate).
                    adv_p = (due_p & edge_ok(p, l)
                             & (prow("aq_hase", l, p) != 0))
                    g2 = cns & adv_p
                    t_entries.append((g2 & ~fcl["ok_ent2_t"][pi]
                                      & inr(i32), True, l, i32,
                                      "f_ent2_t", pi))
                    c_entries.append((g2 & ~fcl["ok_ent2_c"][pi]
                                      & inr(i32), True, l, i32,
                                      "f_ent2_c", pi))
                else:
                    t_entries.append((cns & ~fcl["ok_ppli"][pi]
                                      & inr(i32 - 2),
                                      True, p, i32 - 2, "f_ppli", pi))
                c_entries.append((ent_gate & ~fcl["ok_ent_c"][pi]
                                  & inr(i32 - 1), True, l, i32 - 1,
                                  "f_ent_c", pi))
        for n in range(1, N + 1):
            # Top-window rows, gated on GHOST STATE (phys_len > last_index):
            # a clean node can never consume f_topw (the §3 ghost consume
            # requires slot != li, i.e. phys > li), so steady-state gates
            # are all-False and the cond below skips the whole take; only
            # post-truncation catch-up nodes demand rows.
            ghosty = col("phys_len", n).astype(_I32) > li32f[n]
            for j in range(deep_cache.W_TOP):
                tw = (n - 1) * deep_cache.W_TOP + j
                t_entries.append((~fcl["ok_topw"][tw] & ghosty
                                  & inr(li32f[n] + j),
                                  False, n, li32f[n] + j, "f_topw", tw))

        def fc_refill_all(jobs):
            """Serve every refill entry list (ranked, budgeted, one take
            per log array) under ONE shared lax.cond (r6 consolidation:
            the term and cmd takes used to carry separate conds with
            separate distribute chains; election/conflict ticks fire them
            together anyway, and steady-state ticks now skip both in a
            single branch). In steady state every read is patched by
            writes before it is consumed, so most ticks skip the takes
            (and their distribute chains) entirely; only election/conflict
            ticks pay them. `jobs` = [(entries, budget, log_arr,
            is_term), ...]. A job whose gates are all-False inside a fired
            cond takes nothing and changes nothing (got is False
            everywhere), so the merge is bit-exact with the per-job
            conds."""
            any_gate = jnp.zeros((), dtype=bool)
            for entries, _b, _arr, _t in jobs:
                for gate, *_ in entries:
                    any_gate = any_gate | jnp.any(gate)
            keys_idx = [[(key, idx) for _, _, _, _, key, idx in entries]
                        for entries, _b, _arr, _t in jobs]
            cur_v = [[fcl[key][idx] for key, idx in kj] for kj in keys_idx]
            cur_ok = [[fcl[deep_cache.ok_name(key)][idx]
                       for key, idx in kj] for kj in keys_idx]

            def do(_):
                ov_over = jnp.zeros((G,), dtype=bool)
                flat = []
                for (entries, budget, log_arr, is_term), cvs, coks in zip(
                        jobs, cur_v, cur_ok):
                    rank = jnp.zeros((G,), _I32)
                    rows = jnp.zeros((budget, G), _I32)
                    iota_b = jax.lax.broadcasted_iota(_I32, (budget, G), 0)
                    ranks = []
                    for gate, hard, node, row, key, idx in entries:
                        ranks.append(rank)
                        hot = (iota_b == rank[None]) & gate[None]
                        rows = jnp.where(
                            hot,
                            ((node - 1) * C
                             + jnp.clip(row, 0, C - 1))[None],
                            rows)
                        rank = rank + gate.astype(_I32)
                    vals = jnp.take_along_axis(
                        log_arr, rows, axis=0).astype(_I32)
                    # Overlay this tick's deferred (phase-0) writes: the
                    # take read the pre-tick backing store, the cache must
                    # hold the logical current value.
                    for n2 in range(1, N + 1):
                        for prow_w, pt_w, pc_w, pwr_w in pending[n2]:
                            hit = pwr_w[None] & (
                                rows == ((n2 - 1) * C
                                         + prow_w.astype(_I32))[None])
                            pv = rt(pt_w if is_term else pc_w)
                            vals = jnp.where(hit, pv[None], vals)
                    out_v, out_ok = [], []
                    for (gate, hard, node, row, key, idx), r, cv, cok in \
                            zip(entries, ranks, cvs, coks):
                        got = gate & (r < budget)
                        oh = (iota_b == r[None]) & got[None]
                        v = jnp.sum(jnp.where(oh, vals, 0), axis=0)
                        out_v.append(jnp.where(got, v, cv))
                        out_ok.append(cok | got)
                        if hard:
                            ov_over = ov_over | (gate & ~got)
                    flat += [jnp.stack(out_v), jnp.stack(out_ok)]
                return tuple(flat) + (ov_over,)

            def skip_all(_):
                flat = []
                for cvs, coks in zip(cur_v, cur_ok):
                    flat += [jnp.stack(cvs), jnp.stack(coks)]
                return tuple(flat) + (jnp.zeros((G,), dtype=bool),)

            outs = lax.cond(any_gate, do, skip_all, None)
            for j, kj in enumerate(keys_idx):
                nv, nok = outs[2 * j], outs[2 * j + 1]
                for k2, (key, idx) in enumerate(kj):
                    fcl[key][idx] = nv[k2]
                    fcl[deep_cache.ok_name(key)][idx] = nok[k2]
            return outs[-1]

        tb = deep_cache.TERM_BUDGET_MB if flags.delay \
            else deep_cache.TERM_BUDGET
        cb = deep_cache.CMD_BUDGET_MB if flags.delay \
            else deep_cache.CMD_BUDGET
        fc_ov["v"] = fc_ov["v"] | fc_refill_all(
            [(t_entries, tb, s["log_term"], True),
             (c_entries, cb, s["log_cmd"], False)])

    if batched_logs and not use_fc:
        # ALL of the tick's remaining log reads batched up front. Row
        # indices are known post-phase-4 (see the engine note above); writes
        # that land between here and a pair's consume point are overlaid by
        # patch().
        i_all = {(a, b): prow("next_index", a, b)
                 for a in range(1, N + 1) for b in range(1, N + 1)}
        brows_t, bvals_t, brows_c, bvals_c = {}, {}, {}, {}
        if flags.delay:
            # MAILBOX batch (delay_lo >= 1 — the known-delivery regime):
            #   - the delivery handler's prevLog check on n reads the
            #     slot's own snapshot row aq_pli(l, n) — pre-tick state,
            #     unwritten until that pair's own send (which runs AFTER
            #     its delivery in the canonical order);
            #   - a pair's next_index at its send is ni + d with d in
            #     {-1, 0, +1} decided solely by that pair's single
            #     delivery (capacity-1 slots; delay_lo >= 1 forbids
            #     same-tick redelivery), so the send reads live in the
            #     static window [ni-3, ni] — batch all 4 term candidates
            #     (3 cmd candidates) and select by d at consume time;
            #   - the tick-end last_term ghost rows sit at aq_pli(l, n)+1
            #     (a delivery add at index aq_pli + 1 moves last_index to
            #     aq_pli + 2, exposing the stale stored row beneath it).
            # Node n's log_term batch rows:
            #   [0, 4N)      leader-send candidates ni(n, q) - 3 + k
            #                (k-th block of N at [k*N, (k+1)*N))
            #   [4N, 5N)     n-as-peer delivery prevLog rows aq_pli(l, n)
            #   5N           last_index - 1 (the tick-end last_term base)
            #   [5N+1, 6N+1) n-as-peer ghost rows aq_pli(l, n) + 1
            # log_cmd rows: the 3 entry candidates ni(n, q) - 2 + k.
            T_DEL, T_LLT, T_GHOST = 4 * N, 5 * N, 5 * N + 1
            for n in range(1, N + 1):
                ni_n = [i_all[(n, q)].astype(_I32) for q in range(1, N + 1)]
                aqp_n = [prow("aq_pli", l2, n).astype(_I32)
                         for l2 in range(1, N + 1)]
                brows_t[n] = (
                    sum(([jnp.clip(v - 3 + k, 0, C - 1) for v in ni_n]
                         for k in range(4)), [])
                    + [jnp.clip(v, 0, C - 1) for v in aqp_n]
                    + [jnp.clip(col("last_index", n).astype(_I32) - 1,
                                0, C - 1)]
                    + [jnp.clip(v + 1, 0, C - 1) for v in aqp_n]
                )
                brows_c[n] = brows_t[n][N:4 * N]
            Rt, Rc = 6 * N + 1, 3 * N
        else:
            # Synchronous batch. Node n's batch rows (log_term):
            #   [0, N)    prevLog reads of n-as-leader (pli(n, q))
            #   [N, 2N)   entry reads of n-as-leader (i(n, q) - 1)
            #   [2N, 3N)  n-as-peer prevLog checks (pli(l, n))
            #   3N        last_index - 1 (the tick-end last_term base)
            #   [3N+1, 4N+1) n-as-peer GHOST rows (i(l, n) - 1): a §3
            #     ghost append (post-truncation, phys_len > last_index)
            #     writes slot phys_len while moving last_index to
            #     i(l, n) + 1, so the tick-end cache must read the STALE
            #     stored value at i(l, n) — a row no write covers (the
            #     round-4 review's tick-129 last_term divergence;
            #     tests/test_deep_gather.py pins it).
            # log_cmd rows: [0, N) entry reads. The final scatter needs no
            # current-value rows: masked writes carry out-of-range rows
            # and are DROPPED (mode="drop"), and duplicate real rows are
            # pre-resolved to the last write's value.
            T_LLT, T_GHOST = 3 * N, 3 * N + 1
            # §15 (compact): takes address RING SLOTS; the parallel bpos_t
            # POSITION lists feed the tick-end ghost overlay's equality
            # tests (two distinct positions can share a ring slot, so slot
            # equality is not position equality there).
            rslot = ((lambda x: ring(jnp.maximum(x.astype(_I32), 0)))
                     if compact else (lambda x: jnp.clip(x, 0, C - 1)))
            bpos_t = {}
            for n in range(1, N + 1):
                bpos_t[n] = (
                    [i_all[(n, q)] - 2 for q in range(1, N + 1)]
                    + [i_all[(n, q)] - 1 for q in range(1, N + 1)]
                    + [i_all[(l, n)] - 2 for l in range(1, N + 1)]
                    + [col("last_index", n) - 1]
                    + [i_all[(l, n)] - 1 for l in range(1, N + 1)]
                )
                brows_t[n] = [rslot(x) for x in bpos_t[n]]
                brows_c[n] = brows_t[n][N:2 * N]
            Rt, Rc = 4 * N + 1, N
        from raft_kotlin_tpu.ops import deep_gather

        gather = None
        if not deep_gather.DISABLE:
            gather = deep_gather.build_gather(
                N, C, Rt, Rc, str(ldt_b), G,
                jax.default_backend() == "cpu")
        if gather is not None:
            # One pallas_call: the whole log crosses HBM exactly once; all
            # row extraction happens in VMEM (see ops/deep_gather.py for the
            # measured XLA-gather cost model this replaces).
            vt, vc = gather(
                s["log_term"], s["log_cmd"],
                jnp.concatenate([jnp.stack(brows_t[n])
                                 for n in range(1, N + 1)]),
                jnp.concatenate([jnp.stack(brows_c[n])
                                 for n in range(1, N + 1)]),
            )
            for n in range(1, N + 1):
                bvals_t[n] = vt[(n - 1) * Rt: n * Rt].astype(_I32)
                bvals_c[n] = vc[(n - 1) * Rc: n * Rc].astype(_I32)
        else:
            # FLAT-MERGED takes (round 5): ONE take_along_axis per log array
            # for ALL nodes' read rows, on the flat (N*C, G) layout with
            # global rows. The round-5 on-chip probe
            # (scripts/probe_deep_costs.py) measures the XLA:TPU gather at
            # ~4-5 ms PER OP at G=13k — nearly independent of C AND of row
            # count (~0.15 ms marginal per row) — so the per-op floor, not
            # the row count, dominated the old 2-takes-per-node form
            # (2N ops = ~86 ms of the 96 ms scalar-output tick attribution).
            # Rows are already clipped to [0, C), so offsetting by the
            # node's base cannot alias a neighbor's rows.
            # Widen BEFORE offsetting: local rows may be int16 (NARROW16
            # next_index/last_index) and (n-1)*C exceeds int16 at deep C.
            rows_t_flat = jnp.concatenate(
                [jnp.stack(brows_t[n]).astype(_I32) + (n - 1) * C
                 for n in range(1, N + 1)])
            rows_c_flat = jnp.concatenate(
                [jnp.stack(brows_c[n]).astype(_I32) + (n - 1) * C
                 for n in range(1, N + 1)])
            vt = jnp.take_along_axis(s["log_term"], rows_t_flat, axis=0)
            vc = jnp.take_along_axis(s["log_cmd"], rows_c_flat, axis=0)
            for n in range(1, N + 1):
                bvals_t[n] = vt[(n - 1) * Rt: n * Rt].astype(_I32)
                bvals_c[n] = vc[(n - 1) * Rc: n * Rc].astype(_I32)

    for l in range(1, N + 1):
        raw_armed = col("hb_armed", l)
        armed = raw_armed & col("up", l)
        waiting = armed & (col("hb_left", l) > 0)
        fire = armed & ~waiting
        setcol("hb_left", l, waiting, col("hb_left", l) - 1)
        l_is_f = col("role", l) == FOLLOWER
        # FOLLOWER cancels future firings but this round still goes out
        # (TimerTask.cancel semantics, RaftServer.kt:117).
        if view:
            view["hb_armed"][l - 1] = raw_armed & ~(fire & l_is_f)
        else:
            s["hb_armed"] = _set_row(s["hb_armed"], l - 1,
                                     raw_armed & ~(fire & l_is_f))
        setcol("hb_left", l, fire & ~l_is_f, cfg.hb_ticks - 1)
        for p in range(1, N + 1):
            if flags.delay:
                # In-flight slot from an earlier tick. The known-delivery
                # engines serve the handler's prevLog check up front: from
                # the batch (row = the slot's own aq_pli snapshot,
                # unwritten since batch time — the pair's send runs after
                # its delivery) or from the f_ppli cache (valid only while
                # the snapshot still sits at the live frontier ni - 2;
                # win-jumps/restarts break that and raise OV, never bits).
                if use_fc:
                    aqp32 = prow("aq_pli", l, p).astype(_I32)
                    pi_d = pair(l, p)
                    need_d = (adue0[(l, p)] & edge_ok(p, l)
                              & (aqp32 >= 0)
                              & (col("last_index", p).astype(_I32) > aqp32))
                    fc_ov["v"] = fc_ov["v"] | (need_d & (
                        (aqp32
                         != prow("next_index", l, p).astype(_I32) - 2)
                        | ~fcl["ok_ppli"][pi_d]))
                    append_deliver(l, p,
                                   p_plt=bounded(aqp32, fcl["f_ppli"][pi_d]),
                                   due=adue0[(l, p)])
                elif batched_logs:
                    aqp32 = prow("aq_pli", l, p).astype(_I32)
                    raw_d = patch("log_term", p, brows_t[p][T_DEL + l - 1],
                                  bvals_t[p][T_DEL + l - 1])
                    append_deliver(l, p, p_plt=bounded(aqp32, raw_d),
                                   due=adue0[(l, p)])
                else:
                    append_deliver(l, p, due=adue0[(l, p)])

            # Request construction + §5 skip rules, from l's live state at send
            # (post-delivery: a delivery just above may have advanced next_index).
            li_l = col("last_index", l)
            i = prow("next_index", l, p)
            pli = i - 2
            # prevLogTerm: invalid get -> exception -> skip peer (§6 skip
            # rule). ~(pli < li) is pli >= li — one compare, not compare+not
            # (last_index is the deep input here).
            skip = (pli >= 0) & (pli >= li_l)
            if compact:
                # §15 InstallSnapshot send condition: the peer's frontier
                # fell at/below l's snapshot base — the append path cannot
                # serve it (the entries are folded). b_l >= 1 keeps the
                # base-0 case on the historical quirk-i path.
                b_l = col("snap_index", l).astype(_I32)
                inst = fire & (i.astype(_I32) <= b_l) & (b_l >= 1)
            if use_fc:
                # Frontier-cache consume: the cached values ARE the rows
                # the old prefetch would have taken (ops/deep_cache.py);
                # a consumed-invalid entry raises ov — the runner discards
                # the call's bits and re-runs on the plain engine. The ov
                # guard uses the LIVE fire/skip masks, NOT the refill-time
                # fc_cons snapshot: an earlier-iterating leader's append
                # can raise THIS leader's last_index mid-loop and flip
                # skip/has_entry, making a read needed that the snapshot
                # did not demand — that case must fall back, not silently
                # consume a stale value.
                pi_lp = pair(l, p)
                live_cons = fire & ~skip
                in_pli = inr(pli)
                plt = jnp.where(pli >= 0,
                                bounded(pli, fcl["f_pli"][pi_lp]), -1)
                # Accumulated into fc_ov in ONE merged or below (r6).
                ov_pli = live_cons & in_pli & ~fcl["ok_pli"][pi_lp]
            elif batched_logs and flags.delay:
                # Known-delivery row selection: i = pre-batch ni + d with
                # d = this pair's own delivery outcome (+1 entry success,
                # -1 failure, 0 otherwise — nothing else touches this
                # pair's next_index inside phase 5). Pick among the 4
                # batched candidate rows [ni-3, ni] by d; where clipping
                # collapsed candidates they gathered the same row, so any
                # branch of the select is the same value.
                d32 = i.astype(_I32) - i_all[(l, p)].astype(_I32)

                def _sel(rows, vals, j0, _d=d32, _p=p):
                    j = lambda k: (j0 + k) * N + (_p - 1)
                    r = jnp.where(_d < 0, rows[j(0)],
                                  jnp.where(_d > 0, rows[j(2)], rows[j(1)]))
                    v = jnp.where(_d < 0, vals[j(0)],
                                  jnp.where(_d > 0, vals[j(2)], vals[j(1)]))
                    return r, v

                r_pli, v_pli = _sel(brows_t[l], bvals_t[l], 0)
                plt = jnp.where(
                    pli >= 0,
                    bounded(pli, patch("log_term", l, r_pli, v_pli)), -1)
            elif batched_logs:
                raw_plt = bounded(pli, patch(
                    "log_term", l, brows_t[l][p - 1], bvals_t[l][p - 1]), l)
                if compact:
                    # §15 boundary: prevLog at l's own base-1 is snap_term.
                    raw_plt = jnp.where(pli.astype(_I32) == b_l - 1,
                                        col("snap_term", l).astype(_I32),
                                        raw_plt)
                plt = jnp.where(pli >= 0, raw_plt, -1)
            else:
                plt = jnp.where(pli >= 0, log_term_b(l, pli), -1)
            has_entry = li_l >= i
            skip = skip | (has_entry & (i <= 0))  # quirk i underflow
            if use_fc:
                ent_t = bounded(i - 1, fcl["f_ent_t"][pi_lp])
                ent_c = bounded(i - 1, fcl["f_ent_c"][pi_lp])
                p_plt_b = bounded(pli, fcl["f_ppli"][pi_lp])
                live_cons = fire & ~skip  # post-underflow-quirk skip
                # Mailbox sends snapshot the PHYSICAL row i-1 into the
                # slot whether or not an entry rides along (see ent_gate
                # at the refill) — the guard must cover heartbeat sends
                # too; the sync engine only consumes ent with an entry.
                need_e = live_cons & inr(i - 1) if flags.delay \
                    else live_cons & has_entry & inr(i - 1)
                # ONE merged ov accumulation per pair (r6: four separate
                # (G,) ors used to land here; the guard set is unchanged —
                # boolean-or is associative, so the flag is bit-identical).
                ov_send = ov_pli | (
                    need_e & (~fcl["ok_ent_t"][pi_lp]
                              | ~fcl["ok_ent_c"][pi_lp]))
                if not flags.delay:
                    # The SYNC exchange consumes f_ppli at the send; under
                    # the mailbox only the DELIVERY leg does (guarded
                    # there) — guarding it here too would OV every post-
                    # win-jump send whose pli is in range, systematically
                    # falling the whole call back on election ticks.
                    ov_send = ov_send | (
                        live_cons & in_pli & ~fcl["ok_ppli"][pi_lp])
                fc_ov["v"] = fc_ov["v"] | ov_send
            elif batched_logs and flags.delay:
                # Entry rows: term candidates sit one block above the plt
                # window (blocks 1..3 = rows ni-2..ni); cmd candidates are
                # the whole cmd batch (blocks 0..2 = rows ni-2..ni).
                r_et, v_et = _sel(brows_t[l], bvals_t[l], 1)
                ent_t = bounded(i - 1, patch("log_term", l, r_et, v_et))
                r_ec, v_ec = _sel(brows_c[l], bvals_c[l], 0)
                ent_c = bounded(i - 1, patch("log_cmd", l, r_ec, v_ec))
            elif batched_logs:
                ent_t = bounded(i - 1, patch(
                    "log_term", l, brows_t[l][N + p - 1],
                    bvals_t[l][N + p - 1]), l)
                ent_c = bounded(i - 1, patch(
                    "log_cmd", l, brows_c[l][p - 1], bvals_c[l][p - 1]), l)
                p_plt_b = bounded(pli, patch(
                    "log_term", p, brows_t[p][2 * N + l - 1],
                    bvals_t[p][2 * N + l - 1]), p)
            else:
                ent_t, ent_c = log_gather_tc(l, i - 1)
            if flags.delay:
                # request leg at send tick; skip (the deep input) joins last
                att = (fire & edge_ok(l, p)) & ~skip
                if compact:
                    # §15: install sends ride the SAME slot, discriminated
                    # by aq_hase == 2; the snapshot triple occupies the
                    # pli/plt/ent_t seats. Lanes taking the install path
                    # are excluded from the append send (disjoint masks,
                    # one merged put per field).
                    att_i = inst & edge_ok(l, p)
                    att = att & ~inst
                    a_any = att | att_i
                    h_dt = prow("aq_hase", l, p).dtype
                    put_pair("aq_term", l, p, a_any, col("term", l))
                    put_pair("aq_commit", l, p, a_any, col("commit", l))
                    put_pair("aq_pli", l, p, a_any,
                             jnp.where(att_i, b_l,
                                       pli.astype(_I32)).astype(
                                           prow("aq_pli", l, p).dtype))
                    put_pair("aq_plt", l, p, a_any,
                             jnp.where(att_i,
                                       col("snap_term", l).astype(_I32),
                                       plt))
                    put_pair("aq_hase", l, p, a_any,
                             jnp.where(att_i, jnp.asarray(2, h_dt),
                                       has_entry.astype(h_dt)))
                    put_pair("aq_ent_t", l, p, a_any,
                             jnp.where(att_i,
                                       col("snap_digest", l).astype(_I32),
                                       ent_t))
                    put_pair("aq_ent_c", l, p, a_any,
                             jnp.where(att_i, 0, ent_c))
                    put_pair("aq_due", l, p, a_any, delay_for(l, p))
                else:
                    put_pair("aq_term", l, p, att, col("term", l))
                    put_pair("aq_commit", l, p, att, col("commit", l))
                    put_pair("aq_pli", l, p, att, pli)
                    put_pair("aq_plt", l, p, att, plt)
                    put_pair("aq_hase", l, p, att,
                             has_entry.astype(prow("aq_hase", l, p).dtype))
                    put_pair("aq_ent_t", l, p, att, ent_t)
                    put_pair("aq_ent_c", l, p, att, ent_c)
                    put_pair("aq_due", l, p, att, delay_for(l, p))
                if cfg.delay_lo == 0:
                    append_deliver(l, p)  # τ=0: same-iteration delivery
            else:
                # ~a | ~b = ~(a & b): the two edge legs pre-combine off the
                # skip chain and join it in one op.
                skip = skip | ~(edge_ok(l, p) & edge_ok(p, l))
                act5 = fire & ~skip
                if compact:
                    both_edges = edge_ok(l, p) & edge_ok(p, l)
                    act5 = act5 & ~inst
                    append_exchange(l, p, act5, col("term", l),
                                    col("commit", l), pli, plt, has_entry,
                                    ent_t, ent_c,
                                    p_plt=p_plt_b if batched_logs else None)
                    install_exchange(l, p, inst & both_edges,
                                     col("term", l), b_l,
                                     col("snap_term", l),
                                     col("snap_digest", l),
                                     col("commit", l))
                else:
                    append_exchange(l, p, act5, col("term", l),
                                    col("commit", l), pli, plt, has_entry,
                                    ent_t, ent_c,
                                    p_plt=p_plt_b if batched_logs else None)

    if use_columnar:
        exit_cols()

    # §10 end-of-tick: in-flight countdowns advance (sent at t with τ ⇒ due == 0
    # at t+τ's delivery scan).
    if flags.delay:
        for name in ("vq_due", "aq_due"):
            d = s[name]
            s[name] = d - (d > 0).astype(d.dtype)

    if batched_logs:
        # Apply ALL nodes' deferred phase-0/5 writes as ONE flat scatter per
        # log array on the (N*C, G) layout. Round-5 A/B on chip: merged
        # scatters beat per-node (C, G) scatters IN CONTEXT by ~22 ms/tick
        # (134 vs 157 ms at the config-5 shape) even though the ISOLATED
        # per-op cost scales with operand height — the flat form writes
        # s["log_term"] directly and skips the per-node slice rejoin concat,
        # and the while-body scatter updates the donated buffer in place.
        # Masked entries carry local row C; in the flat layout that would
        # alias the NEXT node's row 0, so they redirect to N*C — outside
        # the whole array — and mode="drop" discards them.
        # Duplicate REAL rows within a lane are possible (two leaders
        # appending to the same slot of one node) and XLA scatter order over
        # duplicates is unspecified — so every entry is first resolved to
        # the LAST real write at its row (chronological pass over this
        # node's entries; rows never alias ACROSS nodes): duplicates then
        # carry identical values and the scatter is deterministic.
        per_node = {}  # n -> (local rows list, resolved term list, cmd list)
        for n in range(1, N + 1):
            writes = pending[n]
            if not writes:
                continue
            rows_l = [w[0].astype(_I32) for w in writes]  # local; C = dropped
            eff_t, eff_c = [], []
            for rk, tk, ck, _wk in writes:
                et, ec = tk.astype(ldt_b), ck.astype(ldt_b)
                for rj, tj, cj, wj in writes:
                    hit = wj & (rj == rk)
                    et = jnp.where(hit, tj.astype(ldt_b), et)
                    ec = jnp.where(hit, cj.astype(ldt_b), ec)
                eff_t.append(et)
                eff_c.append(ec)
            per_node[n] = (rows_l, eff_t, eff_c)
        if per_node:
            from raft_kotlin_tpu.ops import deep_scatter

            G_l = s["log_term"].shape[-1]
            Kmax = max(len(r) for r, _, _ in per_node.values())
            sc = None
            backend = jax.default_backend()
            # Gate on tpu/cpu (ADVICE r5 finding 2): on any OTHER
            # accelerator the Mosaic-shaped kernel fails at compile time
            # inside the jitted tick with no fallback; the XLA flat-scatter
            # branch below works everywhere.
            if not deep_scatter.DISABLE and backend in ("tpu", "cpu"):
                sc = deep_scatter.build_scatter(
                    N, C, Kmax, str(ldt_b), G_l, backend == "cpu",
                    dma=not deep_scatter.FORCE_GRID)
            if sc is not None:
                # One Pallas pass over both logs: the whole log crosses HBM
                # exactly once (read + write) and the K-deep one-hot select
                # chain replaces the XLA scatter lowering (see
                # ops/deep_scatter.py for the cost model).
                def padded(items, fill):
                    # Node slabs padded to Kmax entries; row C = dropped.
                    out = list(items)
                    while len(out) < Kmax:
                        out.append(jnp.full((G_l,), fill, _I32))
                    return out

                def slab(idx, fill):
                    return sum((padded(per_node[n][idx]
                                       if n in per_node else [], fill)
                                for n in range(1, N + 1)), [])

                rows_all = jnp.stack(slab(0, C))
                vt_all = jnp.stack(
                    [v.astype(ldt_b) for v in slab(1, 0)])
                vc_all = jnp.stack(
                    [v.astype(ldt_b) for v in slab(2, 0)])
                s["log_term"], s["log_cmd"] = sc(
                    s["log_term"], s["log_cmd"], rows_all, vt_all, vc_all)
            else:
                # XLA fallback: ONE flat scatter per array. Masked entries
                # carry local row C; in the flat layout that would alias the
                # NEXT node's row 0, so redirect to N*C — outside the whole
                # array — and mode="drop" discards them.
                all_rows, all_t, all_c = [], [], []
                for n, (rows_l, eff_t, eff_c) in per_node.items():
                    rows = jnp.stack(rows_l)
                    all_rows.append(
                        jnp.where(rows >= C, N * C, rows + (n - 1) * C))
                    all_t.append(jnp.stack(eff_t))
                    all_c.append(jnp.stack(eff_c))
                rows_cat = jnp.concatenate(all_rows)
                s["log_term"] = jnp.put_along_axis(
                    s["log_term"], rows_cat, jnp.concatenate(all_t), axis=0,
                    inplace=False, mode="drop")
                s["log_cmd"] = jnp.put_along_axis(
                    s["log_cmd"], rows_cat, jnp.concatenate(all_c), axis=0,
                    inplace=False, mode="drop")

    # lastLogTerm cache refresh (state.last_term): recomputed from the FINAL
    # log, so the ghost-append quirk (§3) is honored exactly — the cache is
    # log_term[last_index - 1], which after a post-truncation append is NOT
    # the term just written. Net-neutral op count for the one-hot and
    # per-pair engines (it replaces the N gathers phase 3 used to issue);
    # the batched engine reads its prefetched last_index-1 base row and
    # overlays this tick's pending writes (a lane whose last_index moved got
    # its new top slot written this tick, so patch() supplies it). The
    # frontier-cache engine maintains last_term LIVE inside log_add (the
    # ghost value comes from f_top), so it skips this pass entirely.
    for n in (() if use_fc else range(1, N + 1)):
        li_f = s["last_index"][n - 1]
        if batched_logs:
            # Stored-value candidates for the final last_index - 1 row: the
            # prefetch-time base (li unchanged) plus the ghost rows (li moved
            # by an append; see the batch-row comment). This tick's writes
            # overlay LAST via patch() — a ghost row that was also written
            # this tick must report the written value. §15 (compact): the
            # overlay matches on POSITIONS (bpos_t) — ring-slot equality is
            # not position equality — and a fully folded window (li ==
            # base, e.g. right after an install) reads snap_term.
            if compact:
                pos_lt = li_f.astype(_I32) - 1
                row = ring(jnp.maximum(pos_lt, 0))
                raw = bvals_t[n][T_LLT]
                for j in range(T_GHOST, T_GHOST + N):
                    raw = jnp.where(bpos_t[n][j].astype(_I32) == pos_lt,
                                    bvals_t[n][j], raw)
                raw = patch("log_term", n, row, raw)
                raw = jnp.where(
                    li_f.astype(_I32) == s["snap_index"][n - 1].astype(_I32),
                    s["snap_term"][n - 1].astype(_I32), raw)
            else:
                row = jnp.clip(li_f - 1, 0, C - 1)
                raw = bvals_t[n][T_LLT]
                for j in range(T_GHOST, T_GHOST + N):
                    raw = jnp.where(brows_t[n][j] == row, bvals_t[n][j], raw)
                raw = patch("log_term", n, row, raw)
            v = jnp.where(li_f >= 1, raw, 0)
        else:
            v = log_term_b(n, li_f - 1)
            if compact:
                v = jnp.where(li_f >= 1, v, 0)
        s["last_term"] = _set_row(s["last_term"], n - 1, v)

    if use_slices and not batched_logs:
        # Rejoin the per-node log slices into the flat (N*C, G) layout.
        # (The batched engine never writes the slices — its deferred writes
        # land in the flat arrays directly via the merged scatter above.)
        s["log_term"] = jnp.concatenate(lt, axis=0)
        s["log_cmd"] = jnp.concatenate(lc, axis=0)

    # -- phase C: §15 snapshot fold (compaction) ----------------------------
    # End of tick, on the FINAL log: every live node whose unfolded
    # committed backlog commit - snap_index has reached the watermark folds
    # up to compact_chunk oldest committed entries into its snapshot
    # (digest' = digest * DIGEST_MULT + cmd, wrapping i32; snap_term = the
    # last folded entry's term) and advances snap_index — which IS the ring
    # base, so the window slides with no data movement. The chunk bound
    # keeps the fold a fixed-shape vector op; steady state keeps ~watermark
    # committed entries unfolded (the laggard-catch-up retention margin).
    if compact:
        for n in range(1, N + 1):
            cm_f = s["commit"][n - 1].astype(_I32)
            si_f = s["snap_index"][n - 1].astype(_I32)
            avail = cm_f - si_f
            due_f = (s["up"][n - 1] != 0) & (avail >= W_cmp)
            cnt_f = jnp.where(due_f, jnp.minimum(avail, CH_cmp), 0)
            dg_f = s["snap_digest"][n - 1].astype(_I32)
            st_f = s["snap_term"][n - 1].astype(_I32)
            lt_f = s["log_term"][(n - 1) * C:n * C]
            lc_f = s["log_cmd"][(n - 1) * C:n * C]
            if flags.dyn_log:
                rows_f = jnp.stack([ring(si_f + j) for j in range(CH_cmp)])
                tvs = jnp.take_along_axis(lt_f, rows_f, axis=0).astype(_I32)
                cvs = jnp.take_along_axis(lc_f, rows_f, axis=0).astype(_I32)
            for j in range(CH_cmp):
                active = due_f & (jnp.asarray(j, _I32) < cnt_f)
                if flags.dyn_log:
                    tv_j, cv_j = tvs[j], cvs[j]
                else:
                    oh_j = logrow_c == ring(si_f + j)[None, :]
                    tv_j = jnp.sum(jnp.where(oh_j, lt_f, 0),
                                   axis=0).astype(_I32)
                    cv_j = jnp.sum(jnp.where(oh_j, lc_f, 0),
                                   axis=0).astype(_I32)
                dg_f = jnp.where(
                    active, dg_f * jnp.asarray(DIGEST_MULT, _I32) + cv_j,
                    dg_f)
                st_f = jnp.where(active, tv_j, st_f)
            s["snap_index"] = _set_row(s["snap_index"], n - 1, si_f + cnt_f)
            s["snap_term"] = _set_row(s["snap_term"], n - 1, st_f)
            s["snap_digest"] = _set_row(s["snap_digest"], n - 1, dg_f)

    if use_fc:
        # Restack the frontier cache + the per-lane overflow flag into the
        # caller's dict (the runner threads them through its scan carry).
        for k in fc_fields:
            fcache[k] = jnp.stack(fcl[k])
        fcache["ov"] = fc_ov["v"]

    flush_resets()
    _ps.close()
    return aux_dirty["m"]


def split_rng(rng):
    """Normalize an rng operand to (base, tkeys, bkeys, scen): classical
    3-tuples (every pre-scenario caller, and make_rng without a scenario)
    carry an empty bank. THE one unpack idiom — every engine routes its
    rng operand through here so the scenario bank reaches make_aux on all
    of them."""
    if len(rng) == 3:
        base, tkeys, bkeys = rng
        return base, tkeys, bkeys, {}
    return rng


def make_flags(cfg: RaftConfig, inject_present: bool = False,
               fault_present: bool = False, batched: Optional[bool] = None,
               sharded: bool = False) -> BodyFlags:
    """The BodyFlags a tick over `cfg` compiles with (shared by make_aux and
    the multi-tick flat-carry runner, which needs the field set up front).
    Scenario banks (cfg.scenario) compile the fault/link phases in when the
    spec carries the corresponding channels — a static property of the
    config, so every engine resolves the same flags."""
    dyn = cfg.uses_dyn_log
    spec = cfg.scenario
    return BodyFlags(
        faults=cfg.p_crash > 0 or cfg.p_restart > 0 or fault_present
        or (spec is not None and spec.has_faults),
        links=cfg.p_link_fail > 0 or cfg.p_link_heal > 0
        or (spec is not None and spec.has_links),
        periodic=cfg.cmd_period > 0,
        inject=inject_present,
        delay=cfg.uses_mailbox,
        # Deep logs switch to dynamic gather/scatter addressing (the Pallas
        # builder forces this back off — Mosaic needs the one-hot form, and
        # deep-log configs never reach Pallas anyway via choose_impl).
        dyn_log=dyn,
        # Mailbox configs take the batched engines only in the
        # known-delivery regime (delay_lo >= 1 — see BodyFlags.batched);
        # τ=0 stays per-pair on every path, even when `batched` pins True.
        # §15 compaction under the mailbox also pins per-pair: an install
        # delivery JUMPS next_index, breaking the batched engine's static
        # row-window invariant (BodyFlags.compact).
        batched=dyn and (not cfg.uses_mailbox or cfg.known_delivery)
        and not (cfg.uses_mailbox and cfg.uses_compaction)
        and batched is not False,
        sharded=dyn and sharded,
        compact=cfg.uses_compaction,
    )


def make_aux(cfg: RaftConfig, base, tkeys, bkeys, state: RaftState,
             inject, fault_cmd, batched: Optional[bool] = None,
             sharded: bool = False, scen: Optional[dict] = None):
    """Draw/assemble the phase_body aux inputs from pre-tick state (XLA ops).

    Randomness is drawn in the canonical (G, ...) §4 shapes and transposed, so no
    drawn bit depends on the groups-minor layout. Returns (aux dict, flags).
    `batched=False` forces the per-pair deep-log engine (sharded runs — see
    BodyFlags.batched); None = automatic (batched whenever dyn and no mailbox).
    `sharded=True` marks an actually-sharded run (parallel/mesh): the per-pair
    dyn engine then uses the flat log layout (BodyFlags.sharded).

    `scen` is the per-group ScenarioBank (SEMANTICS.md §12; rides the rng
    operand — split_rng): per-group fault thresholds replace the scalar
    probabilities channel-by-channel, per-group delay windows replace the
    scalar window, and scripted partition programs fold into edge_iid as
    time-windowed directed-link masks — all HERE, so phase_body and the
    Mosaic kernel never see a scenario at all. Leader-isolation programs
    read the PRE-TICK roles from `state` (engines that feed a stateless
    shim cannot run them and must fall back — cfg.scenario.needs_state)."""
    G, N = cfg.n_groups, cfg.n_nodes
    t = state.tick
    scen = scen or {}
    aux = {}
    flags = make_flags(cfg, inject_present=inject is not None,
                       fault_present=fault_cmd is not None,
                       batched=batched, sharded=sharded)
    if flags.delay and cfg.delay_lo < cfg.delay_hi:
        aux["delay"] = rngmod.delay_mask(
            base, t, (G, N, N), cfg.delay_lo, cfg.delay_hi,
            lo_g=scen.get("delay_lo"), hi_g=scen.get("delay_hi")
        ).transpose(1, 2, 0).reshape(N * N, G).astype(jnp.int16)
    edge = rngmod.edge_ok_mask(
        base, t, (G, N, N), cfg.p_drop, thresh=scen.get("drop_t"))
    if "part_kind" in scen:
        # Scripted partitions (§12): evaluated on the canonical (G, N, N)
        # orientation BEFORE the kernel transpose, from pre-tick state.
        role = getattr(state, "role", None)
        up = getattr(state, "up", None)
        if cfg.scenario is not None and cfg.scenario.needs_state \
                and role is None:
            raise RuntimeError(
                "leader-isolation partition programs need the pre-tick "
                "state (cfg.scenario.needs_state) — this engine feeds a "
                "stateless aux shim and must fall back")
        lead = None
        if role is not None:
            # (N, G) state rows -> canonical (G, N); up may be an int
            # stand-in on the flat carry.
            lead = ((role == LEADER) & (up != 0)).T
        edge = edge & ~rngmod.scenario_link_down(scen, t, lead, N)
    aux["edge_iid"] = edge.transpose(1, 2, 0).reshape(N * N, G) \
        .astype(jnp.int16)
    if flags.faults:
        crash_c = rngmod.event_mask(
            base, rngmod.KIND_CRASH, t, (G, N), cfg.p_crash,
            thresh=scen.get("crash_t"))
        restart_c = rngmod.event_mask(
            base, rngmod.KIND_RESTART, t, (G, N), cfg.p_restart,
            thresh=scen.get("restart_t"))
        # §15 warmup-down: deterministic hold/rejoin on the canonical
        # orientation BEFORE the kernel transpose (no draws consumed).
        crash_c, restart_c = rngmod.apply_warmup_faults(
            cfg.scenario, cfg.cmd_node, t, crash_c, restart_c)
        crash_m, restart_m = crash_c.T, restart_c.T
        if fault_cmd is not None:
            crash_m = crash_m | (fault_cmd.T == 1)
            restart_m = restart_m | (fault_cmd.T == 2)
        aux["crash_m"], aux["restart_m"] = crash_m, restart_m
        aux["el_draw_f"] = rngmod.draw_uniform_keyed(
            tkeys, state.t_ctr, *el_bounds(cfg, scen)).astype(jnp.int16)
    if flags.links:
        aux["link_fail"] = rngmod.event_mask(
            base, rngmod.KIND_LINK_FAIL, t, (G, N, N), cfg.p_link_fail,
            thresh=scen.get("link_fail_t")
        ).transpose(1, 2, 0).reshape(N * N, G).astype(jnp.int16)
        aux["link_heal"] = rngmod.event_mask(
            base, rngmod.KIND_LINK_HEAL, t, (G, N, N), cfg.p_link_heal,
            thresh=scen.get("link_heal_t")
        ).transpose(1, 2, 0).reshape(N * N, G).astype(jnp.int16)
    aux["bdraw"] = rngmod.draw_uniform_keyed(
        bkeys, state.b_ctr, cfg.bo_lo, cfg.bo_hi).astype(jnp.int16)
    if flags.periodic:
        due = (t % cfg.cmd_period == 0) & (t > 0)
        aux["periodic"] = jnp.where(
            due, jnp.broadcast_to(t, (1, G)), -jnp.ones((1, G), _I32))
    if flags.inject:
        aux["inject"] = inject.T
    return aux, flags


def flatten_state(cfg: RaftConfig, state: RaftState) -> dict:
    """RaftState -> the rank-2 dict phase_body operates on (free reshapes).
    §10 mailbox fields are included iff present on the state (cfg.uses_mailbox)."""
    N, C, G = cfg.n_nodes, cfg.phys_capacity, cfg.n_groups
    fields = (STATE_FIELDS + (MAILBOX_FIELDS if cfg.uses_mailbox else ())
              + (SNAPSHOT_FIELDS if cfg.uses_compaction else ()))
    s = {}
    for k in fields:
        v = getattr(state, k)
        if k in _PAIR_FIELDS:
            v = v.reshape(N * N, G)
            if v.dtype == jnp.bool_:
                v = v.astype(jnp.int16)  # no i1 tensors at pair shape (Mosaic limits)
        elif k in _LOG_FIELDS:
            v = v.reshape(N * C, G)
        s[k] = v
    return s


def unflatten_state(cfg: RaftConfig, s: dict) -> dict:
    """Inverse of flatten_state (still a dict; add the tick scalar to build RaftState)."""
    N, C, G = cfg.n_nodes, cfg.phys_capacity, cfg.n_groups
    out = dict(s)
    for k in _PAIR_FIELDS:
        if k not in out:
            continue  # mailbox fields absent when cfg.uses_mailbox is off
        v = out[k].reshape(N, N, G)
        if k in ("responded", "link_up"):
            v = v != 0
        out[k] = v
    for k in _LOG_FIELDS:
        out[k] = out[k].reshape(N, C, G)
    return out


def el_bounds(cfg: RaftConfig, scen):
    """The election-timeout bounds every engine draws against: the scalar
    config window, or — under §19 timeout_windows — the bank's per-group
    [el_lo, el_hi] rows broadcast over the (N, G) counter grids. One copy,
    so the boot draw, the phase-F restart redraw and the deferred §7
    materialization can never disagree on the window."""
    if scen and "el_lo" in scen:
        return scen["el_lo"][None, :], scen["el_hi"][None, :]
    return cfg.el_lo, cfg.el_hi


def materialize_el(cfg: RaftConfig, tkeys, s: dict, el_dirty,
                   scen: Optional[dict] = None):
    """The SEMANTICS.md §7 deferred election draw: el_left for dirty nodes is
    the counted draw at t_ctr - 1 (the last counter the tick consumed).
    Shared by finish_tick and the flat-carry Pallas runner so the deferral
    formula lives in exactly one place."""
    d = rngmod.draw_uniform_keyed(tkeys, s["t_ctr"] - 1,
                                  *el_bounds(cfg, scen))
    return jnp.where(el_dirty, d.astype(s["el_left"].dtype), s["el_left"])


def finish_tick(cfg: RaftConfig, tkeys, s: dict, el_dirty, t,
                scen: Optional[dict] = None):
    """Materialize the deferred election draws and bump the tick counter."""
    s["el_left"] = materialize_el(cfg, tkeys, s, el_dirty, scen=scen)
    return RaftState(**s, tick=t + 1)


def make_rng(cfg: RaftConfig, uids=None):
    """The per-simulation RNG operands: (base key, timeout key grid, backoff key
    grid[, scenario bank]). When cfg.scenario is set, the per-group
    ScenarioBank (utils/rng.sample_scenario_bank — keyed by the spec's
    farm_seed/universe_base, NOT cfg.seed) rides the tuple as a 4th
    element, reaching every engine's make_aux through the existing rng
    operand plumbing: bank VALUES are runtime operands, so same-spec-shape
    configs share one compilation. Classical configs keep the 3-tuple
    (split_rng normalizes). `uids` overrides the bank's universe-id row
    (the §19 continuous scheduler's admission hook — see
    sample_scenario_bank); bank values stay runtime operands, so
    admissions never recompile.

    Static key prefixes are computed once per simulation (rng.grid_keys):
    the per-draw cost inside the tick drops to fold_in(counter) + randint.
    grid_keys is (G, N) canonical; transposed here so keyed draws line up with
    (N, G) counter grids (the derivation is per-element, so the draw bits are
    unchanged).

    This tuple is threaded through jit boundaries as RUNTIME OPERANDS, not
    closure constants: the seed then never appears in the compiled program, so
    every same-shape/same-pacing config shares one XLA compilation regardless of
    seed (multi-minute compiles on small hosts make this the difference between
    a usable differential suite and an unusable one)."""
    base = rngmod.base_key(cfg.seed)
    N = cfg.n_nodes
    tkeys = rngmod.grid_keys(base, rngmod.KIND_TIMEOUT, cfg.n_groups, N).T
    bkeys = rngmod.grid_keys(base, rngmod.KIND_BACKOFF, cfg.n_groups, N).T
    if cfg.scenario is not None:
        return base, tkeys, bkeys, rngmod.sample_scenario_bank(cfg, uids=uids)
    assert uids is None, "universe ids need cfg.scenario"
    return base, tkeys, bkeys


def make_tick(cfg: RaftConfig, batched: Optional[bool] = None,
              sharded: bool = False, compute: str = "unpacked"):
    """Build tick(state, inject=None, fault_cmd=None[, rng]) -> state for a
    fixed config. `batched=False` forces the per-pair deep-log engine
    (BodyFlags.batched; used by sharded runs); `sharded=True` additionally
    selects the flat log layout inside it (BodyFlags.sharded — what
    parallel/mesh compiles per shard; exposed here for differential tests).

    `compute="packed"` (SEMANTICS.md §18) is the XLA packed-COMPUTE twin:
    the flat state crosses into the lattice through
    models/state.enter_packed_compute (the vote-exchange set as packed
    words) and back through exit_packed_compute, with
    BodyFlags.packed_compute selecting the popcount-quorum program. The
    external contract is unchanged (wide RaftState in/out, bit-equal
    observables) — this twin exists so the Pallas packed-compute kernel is
    differentially pinnable on CPU (tests/test_packed_compute.py).

    `inject` is an optional (G, N) int32 array of commands (-1 = none) delivered in
    phase 0 in addition to the cfg.cmd_period rule — the driver-level equivalent of the
    reference's GET /cmd/{command} (RaftServer.kt:87-90). `fault_cmd` is an optional
    (G, N) int32 of driver-scheduled §9 events (0 none / 1 crash / 2 restart). Both use
    the driver-canonical (G, N) shape; they are transposed internally.

    `rng` defaults to make_rng(cfg), derived lazily on first use — every outer
    jit wrapper (make_run, Simulator, make_sharded_run) passes it explicitly
    through its jit boundary so the seed stays out of the compiled program
    (see make_rng), and then the default is never materialized.
    """
    if compute not in ("unpacked", "packed"):
        raise ValueError(f"unknown compute {compute!r}")
    default_rng: list = []

    def tick(
        state: RaftState,
        inject: Optional[jax.Array] = None,
        fault_cmd: Optional[jax.Array] = None,
        rng=None,
    ) -> RaftState:
        G = state.term.shape[-1]
        assert G == cfg.n_groups, (
            f"state has {G} groups but make_tick was built for {cfg.n_groups}"
        )
        if rng is None:
            if not default_rng:
                # Eager even when first called under a jit trace: omnistaging
                # would otherwise stage these into the CURRENT trace and the
                # cached tracer would leak into the next (inject/fault)
                # signature's trace (UnexpectedTracerError).
                with jax.ensure_compile_time_eval():
                    default_rng.append(make_rng(cfg))
            rng = default_rng[0]
        base, tkeys, bkeys, scen = split_rng(rng)
        aux, flags = make_aux(cfg, base, tkeys, bkeys, state, inject, fault_cmd,
                              batched=batched, sharded=sharded, scen=scen)
        s = flatten_state(cfg, state)
        if compute == "packed":
            # §18 packed-compute twin: remember the flat dtypes the
            # exchange set entered with so the exit restores them exactly
            # (bit-equal to the wide program, whose lattice preserves
            # entry dtypes).
            wdt = {k: s[k].dtype for k in ("responded", "votes",
                                           "responses")}
            s = enter_packed_compute(cfg, s)
            flags = dataclasses.replace(flags, packed_compute=True)
        el_dirty = phase_body(cfg, s, aux, flags)
        if compute == "packed":
            s = exit_packed_compute(cfg, s, dtypes=wdt)
        return finish_tick(cfg, tkeys, unflatten_state(cfg, s), el_dirty,
                           state.tick, scen=scen)

    return tick


def make_run(cfg: RaftConfig, n_ticks: int, trace: bool = True, impl: str = "xla",
             batched: Optional[bool] = None, telemetry: bool = False,
             monitor: bool = False, rng=None, fused_ticks: int = 1,
             layout: Optional[str] = None, compute: Optional[str] = None,
             serving: bool = False, serving_gen: bool = False):
    """jitted runner: state -> (state, trace) stepping n_ticks via lax.scan.

    trace is a dict of (T, N, G) arrays (role/term/commit/last_index/voted_for/rounds/
    up per tick, post-tick) — the differential-test observable. With trace=False
    returns per-tick (G,) leader counts only (cheap bench/metrics mode).
    impl: "xla" (default), "pallas" (the ops/pallas_tick.py megakernel), or
    "auto" — resolve engine + fused depth through the unified plan layer
    (parallel/autotune.plan_for, r13).
    batched=False forces the per-pair deep-log engine (BodyFlags.batched) —
    XLA:CPU compiles of the batched engine blow up on int16 deep configs, so
    CPU-bound tests of such configs pass this.
    telemetry=True additionally threads the scan-carry flight recorder
    (utils/telemetry.py — scalar counters, read back once);
    monitor=True threads the scan-carry safety-invariant monitor (Figure-3
    checks + first-violation latch + history ring, finalized form; the
    fuzzing farm's per-GROUP stress channel needs the RAW carry and runs
    its own scan — api/fuzz.make_batch_runner). The
    return grows accordingly: (state, trace[, telemetry][, monitor]) —
    protocol bits are unchanged either way (both only read the states the
    scan already carries).
    `rng` overrides the counted-threefry operand (default make_rng(cfg)) —
    bench.measure dispatches reps with per-rep perturbed rng seeds over the
    cfg-seeded initial state, and a faithful replay of such a rep
    (api/triage.triage_violation) must reproduce exactly that split.

    `fused_ticks` = T > 1 (ISSUE 7) is the XLA REFERENCE SCAN of the fused
    Pallas engine: the scan body advances T ticks through a lax.fori_loop,
    so the oracle-side comparison program has the same T-block shape as
    one fused kernel launch (n_ticks % T remainder ticks run per-tick
    after the blocks). Bits are identical to T=1 — the fori_loop body IS
    the per-tick function. Per-tick traces cannot ride a fori_loop, so
    trace=True keeps T=1 (the sticky fallback, matching the Pallas
    routing); with trace=False the per-tick leader counts become per-BLOCK
    (block-end) counts of shape (n_ticks // T, G). Telemetry/monitor
    accumulate per tick inside the loop, bit-equal to T=1.

    `layout` = "packed" (ISSUE 11) carries the PACKED state layout
    (models/state.pack_state — SEMANTICS.md §14) through the scan: the
    body unpacks at read, ticks on the wide dtypes (identical bits by
    construction) and re-packs at write, so the state at rest between
    ticks is the bit/byte-minimal representation. External contract is
    unchanged (wide state in, wide state out); the width-overflow latch
    is host-checked after the run and raises RuntimeError on a wrapped
    value (re-run with layout="wide"). The default None adopts the
    plan's layout under impl="auto" and means "wide" otherwise — an
    EXPLICIT "wide" always wins over the routed plan (it is the
    documented overflow remedy and must never be re-packed).

    `serving` = True (SEMANTICS.md §20; needs cfg.serve_slots > 0) threads
    the scan-carry serving state (ops/serving.py — applied KV planes,
    latency histograms, read gating) advanced on every post-tick state
    exactly like the monitor; the return grows a trailing serving carry.
    `serving_gen` = True additionally feeds each tick the device-resident
    §20 client inject stream (serving.gen_inject — XLA engine only; the
    generator rides phase 0's inject operand, which the Pallas megakernel
    does not take).

    `compute` = "packed" (SEMANTICS.md §18) selects the packed-DOMAIN
    lattice program: the per-tick function evaluates the vote-exchange
    set on packed words (make_tick compute=... / the Pallas kernel's
    packed carry). Orthogonal to `layout` (which packs the state AT REST
    between ticks); bit-equal observables either way. The default None
    adopts the plan's compute under impl="auto" and means "unpacked"
    otherwise.
    """
    from raft_kotlin_tpu.models.state import (
        check_packed_ov, pack_state, unpack_state)

    if impl == "auto":
        # The unified plan layer (parallel/autotune.plan_for, r13): one
        # resolution decides engine + fused depth; this runner no longer
        # needs per-caller impl knowledge ("pallas" stays a pallas-tick
        # advancer here, so only the engine name and T are consumed).
        from raft_kotlin_tpu.parallel.autotune import plan_for

        plan = plan_for(cfg, telemetry=telemetry, monitor=monitor,
                        trace=trace)
        impl = "pallas" if plan["engine"] == "pallas" else "xla"
        if fused_ticks == 1:
            fused_ticks = plan["fused_ticks"]
        if layout is None:
            layout = plan.get("layout", "wide")
        if compute is None:
            compute = plan.get("compute", "unpacked")
    layout = layout or "wide"
    compute = compute or "unpacked"
    packed = layout == "packed"
    if layout not in ("wide", "packed"):
        raise ValueError(f"unknown layout {layout!r}")
    if compute not in ("unpacked", "packed"):
        raise ValueError(f"unknown compute {compute!r}")
    T_f = max(1, fused_ticks)
    if trace:
        T_f = 1  # sticky fallback: per-tick traces need per-tick emission
    if impl == "pallas":
        from raft_kotlin_tpu.ops.pallas_tick import make_pallas_tick

        tick_fn = make_pallas_tick(cfg, compute=compute)
    else:
        tick_fn = make_tick(cfg, batched=batched, compute=compute)
    if rng is None:
        rng = make_rng(cfg)
    if serving or serving_gen:
        from raft_kotlin_tpu.ops import serving as serving_mod

        if not serving_mod.serving_enabled(cfg):
            raise ValueError("serving/serving_gen need cfg.serve_slots > 0")
        if serving_gen and impl != "xla":
            raise ValueError("serving_gen rides phase 0's inject operand "
                             "— XLA engine only")

    @jax.jit
    def run(st, rng):
        if packed:
            st = pack_state(cfg, st)
        if serving or serving_gen:
            base_k, _tk, _bk, scen_b = split_rng(rng)
            kw = rngmod.kt_key_words(base_k)

        def one(carry):
            st, tel, mon, srv = carry
            wide = unpack_state(cfg, st) if packed else st
            inj = None
            if serving_gen:
                inj = serving_mod.gen_inject(cfg, kw[0], kw[1],
                                             srv["tick"], scen=scen_b)
            with telemetry_mod.engine_scope(impl):
                st2 = tick_fn(wide, inject=inj, rng=rng) if inj is not None \
                    else tick_fn(wide, rng=rng)
            if telemetry:
                tel = telemetry_mod.telemetry_step(wide, st2, tel)
            srv_prev = srv
            if serving:
                # Serving advances BEFORE the monitor folds: the §21
                # srv_* series columns read the (prev, cur) serving pair
                # of this same tick.
                srv = serving_mod.serving_step(
                    cfg, serving_mod.serving_view(st2), srv, kw=kw,
                    scen=scen_b)
            elif serving_gen:
                srv = dict(srv, tick=srv["tick"] + 1)
            if monitor:
                pair = (srv_prev, srv) if serving else (None, None)
                mon = telemetry_mod.monitor_step(wide, st2, mon,
                                                 srv_prev=pair[0],
                                                 srv_cur=pair[1])
            nxt = pack_state(cfg, st2, ov=st.ov) if packed else st2
            return (nxt, tel, mon, srv), st2

        def body(carry, _):
            carry, st2 = one(carry)
            if trace:
                out = {
                    "role": st2.role,
                    "term": st2.term,
                    "commit": st2.commit,
                    "last_index": st2.last_index,
                    "voted_for": st2.voted_for,
                    "rounds": st2.rounds,
                    "up": st2.up,
                }
            else:
                out = jnp.sum((st2.role == LEADER).astype(_I32), axis=0)
            return carry, out

        def block(carry, _):
            # One T-block: the fori-loop-over-T body that mirrors a fused
            # kernel launch's program shape (ISSUE 7). The block output
            # reads the block-END state (unpacked again under the packed
            # layout — per-tick wide states cannot ride a fori_loop out).
            carry = lax.fori_loop(0, T_f, lambda _i, c: one(c)[0], carry)
            end = unpack_state(cfg, carry[0]) if packed else carry[0]
            out = jnp.sum((end.role == LEADER).astype(_I32), axis=0)
            return carry, out

        tel0 = telemetry_mod.telemetry_zeros() if telemetry else None
        mon0 = telemetry_mod.monitor_init(cfg.n_groups, n_ticks, monitor,
                                          **telemetry_mod.ops_kw(cfg))
        if serving:
            srv0 = serving_mod.serving_init(cfg)
        elif serving_gen:
            srv0 = {"tick": jnp.zeros((), _I32)}
        else:
            srv0 = None
        carry = (st, tel0, mon0, srv0)
        if T_f > 1:
            n_block, rem = divmod(n_ticks, T_f)
            carry, ys = lax.scan(block, carry, None, length=n_block)
            if rem:
                carry, _ = lax.scan(body, carry, None, length=rem)
        else:
            carry, ys = lax.scan(body, carry, None, length=n_ticks)
        end, tel, mon, srv = carry
        # One scalar reduction of the (G,) per-group latch, at scan exit
        # (never per tick — the sharded runs' collective-freedom hinges
        # on the carry staying lane-shaped).
        pov = jnp.any(end.ov != 0) if packed else None
        if packed:
            end = unpack_state(cfg, end)
        out = (end, ys)
        if telemetry:
            out = out + (tel,)
        if monitor:
            out = out + (telemetry_mod.monitor_finalize(mon),)
        if serving:
            out = out + (srv,)
        return out + (pov,) if packed else out

    # rng rides the jit boundary as an operand (seed-independent program).
    if packed:
        def call(st):
            res = run(st, rng)
            res, pov = res[:-1], res[-1]
            check_packed_ov(pov)  # loud-fail: wrapped bits are invalid
            return res

        return call
    return lambda st: run(st, rng)
