"""The vectorized lockstep tick: all (groups x nodes) advance one SEMANTICS.md tick
inside one jitted, scan-able pure function.

Design (TPU-first, not a port): the reference's threads/timers/RPCs (RaftServer.kt)
become a fixed phase pipeline of elementwise (G,)-wide integer ops — the node loops are
tiny (N ≤ 9) and unrolled at trace time, so group count G is the only data axis and XLA
sees static shapes throughout. State is laid out groups-minor ((N, G), (N, N, G),
(N, C, G) — models/state.py) so every per-node access is a contiguous lane-aligned row.
RPC exchanges are in-array mailbox transactions: each (candidate, peer) /
(leader, peer) pair is one masked vectorized read-modify-write over the G axis, applied
sequentially in the canonical order so the result is bit-identical to the scalar oracle
(models/oracle.py). Quorum tallies are reductions over the node axis. All randomness is
counted threefry (utils/rng.py), drawn in the canonical (G, ...) shapes and transposed
at the boundary.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from raft_kotlin_tpu.models.state import (
    ACTIVE,
    BACKOFF,
    CANDIDATE,
    FOLLOWER,
    IDLE,
    LEADER,
    RaftState,
)
from raft_kotlin_tpu.utils import rng as rngmod
from raft_kotlin_tpu.utils.config import RaftConfig

_I32 = jnp.int32


def make_tick(cfg: RaftConfig):
    """Build tick(state, inject=None, fault_cmd=None) -> state for a fixed config.

    `inject` is an optional (G, N) int32 array of commands (-1 = none) delivered in
    phase 0 in addition to the cfg.cmd_period rule — the driver-level equivalent of the
    reference's GET /cmd/{command} (RaftServer.kt:87-90). `fault_cmd` is an optional
    (G, N) int32 of driver-scheduled §9 events (0 none / 1 crash / 2 restart). Both use
    the driver-canonical (G, N) shape; they are transposed internally.
    """
    N, C, maj = cfg.n_nodes, cfg.log_capacity, cfg.majority
    base = rngmod.base_key(cfg.seed)
    # Static key prefixes, computed once per simulation (rng.grid_keys): the per-draw
    # cost inside the tick drops to fold_in(counter) + randint. grid_keys is (G, N)
    # canonical; transposed here so keyed draws line up with (N, G) counter grids
    # (the derivation is per-element, so the draw bits are unchanged).
    tkeys = rngmod.grid_keys(base, rngmod.KIND_TIMEOUT, cfg.n_groups, N).T
    bkeys = rngmod.grid_keys(base, rngmod.KIND_BACKOFF, cfg.n_groups, N).T

    def tick(
        state: RaftState,
        inject: Optional[jax.Array] = None,
        fault_cmd: Optional[jax.Array] = None,
    ) -> RaftState:
        s = {f.name: getattr(state, f.name) for f in dataclasses.fields(state)}
        G = s["term"].shape[-1]
        assert G == cfg.n_groups, (
            f"state has {G} groups but make_tick was built for {cfg.n_groups}"
        )
        lane = jnp.arange(C, dtype=_I32)
        t = s["tick"]

        # -- small helpers over the mutable dict --------------------------------

        def col(name, n):
            return s[name][n - 1]

        def setcol(name, n, mask, vals):
            cur = s[name][n - 1]
            s[name] = s[name].at[n - 1].set(jnp.where(mask, vals, cur))

        def log_gather(name, n, idx):
            # (G,) read of physical slot idx from node n, as a one-hot contraction
            # over the C sublane axis (no per-lane gather op — TPU-friendly); 0 where
            # idx is out of [0, C) — callers must guard with masks.
            arr = s[name][n - 1]                      # (C, G)
            oh = lane[:, None] == idx[None, :]
            return jnp.sum(jnp.where(oh, arr, 0), axis=0)

        def log_add(n, i, term_v, cmd_v, mask):
            # SEMANTICS.md §3 add(): physical append / reject / overwrite-truncate.
            # One-hot masked write over the C sublane axis instead of a scatter; the
            # write slot is always in-range where the write mask holds (append needs
            # phys_len < C; overwrite needs i < last_index <= C).
            li = col("last_index", n)
            pl = col("phys_len", n)
            app = mask & (i == li) & (pl < C)
            ovw = mask & (i < li) & (i >= 0)
            slot = jnp.where(app, pl, i)
            oh = (lane[:, None] == slot[None, :]) & (app | ovw)[None, :]
            lt = s["log_term"][n - 1]                 # (C, G)
            lc = s["log_cmd"][n - 1]
            s["log_term"] = s["log_term"].at[n - 1].set(
                jnp.where(oh, term_v[None, :], lt)
            )
            s["log_cmd"] = s["log_cmd"].at[n - 1].set(
                jnp.where(oh, cmd_v[None, :], lc)
            )
            setcol("last_index", n, app | ovw, jnp.where(app, li + 1, i + 1))
            setcol("phys_len", n, app, pl + 1)

        # Election-timer resets (SEMANTICS.md §7): each reset consumes one counted
        # draw and leaves el_left at the LAST consumed draw's value. In phases 2-5
        # nothing reads el_left (phase 1 is its only reader), so the draws there are
        # DEFERRED: resets just advance t_ctr and mark the node dirty, and one grid
        # draw at counter t_ctr-1 materializes el_left at end of tick — identical
        # bits, ~50x fewer threefry evaluations per tick. Phase F resets must stay
        # immediate (they precede phase 1 within the same tick).
        aux = {"el_dirty": jnp.zeros((N, G), dtype=bool)}

        def reset_el_timer_col(n, mask):
            ctr = col("t_ctr", n)
            s["el_armed"] = s["el_armed"].at[n - 1].set(col("el_armed", n) | mask)
            setcol("t_ctr", n, mask, ctr + 1)
            aux["el_dirty"] = aux["el_dirty"].at[n - 1].set(
                aux["el_dirty"][n - 1] | mask
            )

        def reset_el_timer_grid(mask):
            s["el_armed"] = s["el_armed"] | mask
            s["t_ctr"] = s["t_ctr"] + mask.astype(_I32)
            aux["el_dirty"] = aux["el_dirty"] | mask

        def reset_el_timer_grid_now(mask):
            d = rngmod.draw_uniform_keyed(tkeys, s["t_ctr"], cfg.el_lo, cfg.el_hi)
            s["el_left"] = jnp.where(mask, d, s["el_left"])
            s["el_armed"] = s["el_armed"] | mask
            s["t_ctr"] = s["t_ctr"] + mask.astype(_I32)

        # -- phase F: fault events (SEMANTICS.md §9) ----------------------------

        has_faults = (
            cfg.p_crash > 0 or cfg.p_restart > 0 or fault_cmd is not None
        )
        if has_faults:
            crash_m = rngmod.event_mask(
                base, rngmod.KIND_CRASH, t, (G, N), cfg.p_crash).T
            restart_m = rngmod.event_mask(
                base, rngmod.KIND_RESTART, t, (G, N), cfg.p_restart).T
            if fault_cmd is not None:
                crash_m = crash_m | (fault_cmd.T == 1)
                restart_m = restart_m | (fault_cmd.T == 2)
            crash_ev = s["up"] & crash_m
            restart_ev = ~s["up"] & restart_m
            s["up"] = (s["up"] & ~crash_ev) | restart_ev
            rst = restart_ev
            zero = jnp.zeros((), _I32)
            s["term"] = jnp.where(rst, zero, s["term"])
            s["voted_for"] = jnp.where(rst, -1, s["voted_for"])
            s["role"] = jnp.where(rst, FOLLOWER, s["role"])
            s["commit"] = jnp.where(rst, zero, s["commit"])
            s["last_index"] = jnp.where(rst, zero, s["last_index"])
            s["phys_len"] = jnp.where(rst, zero, s["phys_len"])
            s["round_state"] = jnp.where(rst, IDLE, s["round_state"])
            for f in ("votes", "responses", "round_left", "round_age", "bo_left"):
                s[f] = jnp.where(rst, zero, s[f])
            # (N, N, G) arrays are owned by their FIRST node axis (candidate/leader).
            s["responded"] = jnp.where(rst[:, None, :], False, s["responded"])
            s["next_index"] = jnp.where(rst[:, None, :], zero, s["next_index"])
            s["match_index"] = jnp.where(rst[:, None, :], zero, s["match_index"])
            s["hb_armed"] = s["hb_armed"] & ~rst
            s["hb_left"] = jnp.where(rst, zero, s["hb_left"])
            reset_el_timer_grid_now(rst)  # phase 1 reads el_left this same tick
        if cfg.p_link_fail > 0 or cfg.p_link_heal > 0:
            lf = rngmod.event_mask(
                base, rngmod.KIND_LINK_FAIL, t, (G, N, N), cfg.p_link_fail
            ).transpose(1, 2, 0)
            lh = rngmod.event_mask(
                base, rngmod.KIND_LINK_HEAL, t, (G, N, N), cfg.p_link_heal
            ).transpose(1, 2, 0)
            s["link_up"] = jnp.where(s["link_up"], ~lf, lh)

        # Effective edge health (§9): iid survival ∧ link health ∧ both ends up.
        # edge[s-1, r-1, g]; drawn canonically as (G, N, N) then transposed.
        edge = rngmod.edge_ok_mask(base, t, (G, N, N), cfg.p_drop).transpose(1, 2, 0)
        edge = edge & s["link_up"] & s["up"][:, None, :] & s["up"][None, :, :]
        up = s["up"]

        # -- phase 0: command injection (quirk k) -------------------------------

        if cfg.cmd_period > 0:
            due = (t % cfg.cmd_period == 0) & (t > 0)
            n = cfg.cmd_node
            mask = jnp.broadcast_to(due, (G,)) & col("up", n)
            log_add(n, col("last_index", n), col("term", n), jnp.broadcast_to(t, (G,)), mask)
        if inject is not None:
            for n in range(1, N + 1):
                cmd = inject[:, n - 1]
                log_add(n, col("last_index", n), col("term", n), cmd, (cmd >= 0) & col("up", n))

        # -- phase 1: timers (independent countdowns) ---------------------------

        armed = s["el_armed"] & up
        left = s["el_left"] - armed.astype(_I32)
        fire = armed & (left <= 0)
        s["el_left"] = left
        s["el_armed"] = s["el_armed"] & ~fire
        s["role"] = jnp.where(fire, CANDIDATE, s["role"])
        start_round = fire

        in_bo = (s["round_state"] == BACKOFF) & up
        bleft = s["bo_left"] - in_bo.astype(_I32)
        bfire = in_bo & (bleft <= 0)
        s["bo_left"] = bleft
        s["round_state"] = jnp.where(bfire, IDLE, s["round_state"])
        start_round = start_round | bfire

        # -- phase 2: round starts ---------------------------------------------

        is_cand = s["role"] == CANDIDATE
        init = start_round & is_cand
        node_ids = jnp.broadcast_to(jnp.arange(1, N + 1, dtype=_I32)[:, None], (N, G))
        s["term"] = s["term"] + init.astype(_I32)
        s["voted_for"] = jnp.where(init, node_ids, s["voted_for"])
        s["votes"] = jnp.where(init, 0, s["votes"])
        s["responses"] = jnp.where(init, 0, s["responses"])
        s["responded"] = jnp.where(init[:, None, :], False, s["responded"])
        s["round_left"] = jnp.where(init, cfg.round_ticks, s["round_left"])
        s["round_age"] = jnp.where(init, 0, s["round_age"])
        s["round_state"] = jnp.where(init, ACTIVE, s["round_state"])
        s["rounds"] = s["rounds"] + init.astype(_I32)
        demoted_bo = start_round & ~is_cand
        s["round_state"] = jnp.where(demoted_bo, IDLE, s["round_state"])
        reset_el_timer_grid(demoted_bo)

        # -- phase 3: vote exchanges --------------------------------------------

        for c in range(1, N + 1):
            c_attempting = (col("round_state", c) == ACTIVE) & (
                col("round_age", c) % cfg.retry_ticks == 0
            )
            for p in range(1, N + 1):
                att = (
                    c_attempting
                    & ~s["responded"][c - 1, p - 1]
                    & edge[c - 1, p - 1]
                    & edge[p - 1, c - 1]
                )
                # Request built from c's live state (RaftServer.kt:200-207).
                c_term = col("term", c)
                c_li = col("last_index", c)
                c_llt = jnp.where(c_li == 0, 0, log_gather("log_term", c, c_li - 1))
                # Vote handler on p (SEMANTICS.md §6.1).
                p_term = col("term", p)
                p_vf = col("voted_for", p)
                p_li = col("last_index", p)
                p_llt = log_gather("log_term", p, p_li - 1)
                rej_stale = (p_li >= 1) & (c_llt < p_llt)
                rej_short = (p_li >= 1) & (c_llt == p_llt) & (c_li < p_li)
                grant_gt = (c_term > p_term) & ~rej_stale & ~rej_short
                granted = jnp.where(
                    c_term < p_term,
                    False,
                    jnp.where(c_term == p_term, p_vf == c, grant_gt),
                )
                adopt = att & grant_gt
                setcol("term", p, adopt, c_term)
                setcol("voted_for", p, adopt, c)
                setcol("role", p, adopt, FOLLOWER)
                reset_el_timer_col(p, adopt)
                resp_term = col("term", p)
                # Candidate tally (RaftServer.kt:209-211).
                s["responded"] = (
                    s["responded"].at[c - 1, p - 1].set(s["responded"][c - 1, p - 1] | att)
                )
                setcol("responses", c, att, col("responses", c) + 1)
                setcol("role", c, att & (resp_term > c_term), FOLLOWER)  # quirk f
                setcol("votes", c, att & granted, col("votes", c) + 1)

        # -- phase 4: round conclusions -----------------------------------------

        act = (s["round_state"] == ACTIVE) & up
        concl = act & ((s["responses"] >= maj) | (s["round_left"] <= 0))
        is_cand = s["role"] == CANDIDATE
        win = concl & is_cand & (s["votes"] >= maj)
        lose = concl & is_cand & ~win
        dem = concl & ~is_cand
        s["role"] = jnp.where(win, LEADER, s["role"])
        s["next_index"] = jnp.where(
            win[:, None, :], (s["commit"] + 1)[:, None, :], s["next_index"]
        )  # quirk b
        s["match_index"] = jnp.where(win[:, None, :], 0, s["match_index"])
        s["hb_armed"] = s["hb_armed"] | win
        s["hb_left"] = jnp.where(win, 0, s["hb_left"])  # initial delay 0
        s["round_state"] = jnp.where(win | dem, IDLE, s["round_state"])
        bdraw = rngmod.draw_uniform_keyed(bkeys, s["b_ctr"], cfg.bo_lo, cfg.bo_hi)
        s["round_state"] = jnp.where(lose, BACKOFF, s["round_state"])
        s["bo_left"] = jnp.where(lose, bdraw, s["bo_left"])
        s["b_ctr"] = s["b_ctr"] + lose.astype(_I32)
        reset_el_timer_grid(dem)
        ongoing = act & ~concl
        s["round_left"] = s["round_left"] - ongoing.astype(_I32)
        s["round_age"] = s["round_age"] + ongoing.astype(_I32)

        # -- phase 5: append / heartbeat ----------------------------------------

        for l in range(1, N + 1):
            raw_armed = col("hb_armed", l)
            armed = raw_armed & col("up", l)
            waiting = armed & (col("hb_left", l) > 0)
            fire = armed & ~waiting
            setcol("hb_left", l, waiting, col("hb_left", l) - 1)
            l_is_f = col("role", l) == FOLLOWER
            # FOLLOWER cancels future firings but this round still goes out
            # (TimerTask.cancel semantics, RaftServer.kt:117).
            s["hb_armed"] = s["hb_armed"].at[l - 1].set(raw_armed & ~(fire & l_is_f))
            setcol("hb_left", l, fire & ~l_is_f, cfg.hb_ticks - 1)
            for p in range(1, N + 1):
                li_l = col("last_index", l)
                i = s["next_index"][l - 1, p - 1]
                pli = i - 2
                # prevLogTerm: invalid get -> exception -> skip peer (§6 skip rule).
                skip = (pli >= 0) & ~(pli < li_l)
                plt = jnp.where(pli >= 0, log_gather("log_term", l, pli), -1)
                has_entry = li_l >= i
                skip = skip | (has_entry & (i <= 0))  # quirk i underflow
                ent_t = log_gather("log_term", l, i - 1)
                ent_c = log_gather("log_cmd", l, i - 1)
                skip = skip | ~edge[l - 1, p - 1] | ~edge[p - 1, l - 1]
                act5 = fire & ~skip
                # --- append handler on p (SEMANTICS.md §6.2) ---
                req_term = col("term", l)
                req_commit = col("commit", l)
                p_term = col("term", p)
                if p != l:
                    adopt = act5 & (req_term > p_term)
                    setcol("term", p, adopt, req_term)
                    setcol("voted_for", p, adopt, -1)
                    setcol("role", p, adopt, FOLLOWER)
                    reset_el_timer_col(p, adopt)
                    setcol("role", p, act5, FOLLOWER)  # quirk d: any foreign append
                    reset_el_timer_col(p, act5)
                p_li = col("last_index", p)
                p_commit = col("commit", p)
                cadv = act5 & (req_commit > p_commit)
                setcol("commit", p, cadv, jnp.minimum(req_commit, p_li))  # quirk e
                p_plt = log_gather("log_term", p, pli)
                succ = (pli == -1) | ((p_li > pli) & (pli >= 0) & (p_plt == plt))
                log_add(p, pli + 1, ent_t, ent_c, act5 & succ & has_entry)
                resp_term = col("term", p)
                # --- leader processes the response (RaftServer.kt:146-168) ---
                if p != l:
                    l_term = col("term", l)
                    demote = act5 & (resp_term > l_term)
                    setcol("term", l, demote, resp_term)
                    setcol("role", l, demote, FOLLOWER)
                    reset_el_timer_col(l, demote)
                else:
                    demote = jnp.zeros((G,), dtype=bool)
                proc = act5 & ~demote & succ
                with_e = proc & has_entry
                nfail = act5 & ~demote & ~succ
                ni = s["next_index"][l - 1, p - 1]
                s["next_index"] = (
                    s["next_index"]
                    .at[l - 1, p - 1]
                    .set(jnp.where(with_e, ni + 1, jnp.where(nfail, ni - 1, ni)))
                )
                mi = s["match_index"][l - 1, p - 1]
                s["match_index"] = (
                    s["match_index"]
                    .at[l - 1, p - 1]
                    .set(jnp.where(with_e, mi + 1, jnp.where(proc & ~has_entry, pli + 1, mi)))
                )
                # Commit advancement (quirk a), evaluated per response.
                l_commit = col("commit", l)
                cnt = jnp.sum(
                    (s["match_index"][l - 1] > l_commit[None, :]).astype(_I32), axis=0
                )
                setcol("commit", l, with_e & (cnt >= maj), l_commit + 1)

        # Materialize the deferred election-timer draws (see reset helpers above):
        # for every node that reset in phases 2-5, el_left = the draw at its last
        # consumed counter.
        dirty = aux["el_dirty"]
        d = rngmod.draw_uniform_keyed(tkeys, s["t_ctr"] - 1, cfg.el_lo, cfg.el_hi)
        s["el_left"] = jnp.where(dirty, d, s["el_left"])

        s["tick"] = t + 1
        return RaftState(**s)

    return tick


def make_run(cfg: RaftConfig, n_ticks: int, trace: bool = True):
    """jitted runner: state -> (state, trace) stepping n_ticks via lax.scan.

    trace is a dict of (T, N, G) arrays (role/term/commit/last_index/voted_for/rounds/
    up per tick, post-tick) — the differential-test observable. With trace=False
    returns per-tick (G,) leader counts only (cheap bench/metrics mode).
    """
    tick_fn = make_tick(cfg)

    def body(st, _):
        st = tick_fn(st)
        if trace:
            out = {
                "role": st.role,
                "term": st.term,
                "commit": st.commit,
                "last_index": st.last_index,
                "voted_for": st.voted_for,
                "rounds": st.rounds,
                "up": st.up,
            }
        else:
            out = jnp.sum((st.role == LEADER).astype(_I32), axis=0)
        return st, out

    @jax.jit
    def run(st):
        return lax.scan(body, st, None, length=n_ticks)

    return run
