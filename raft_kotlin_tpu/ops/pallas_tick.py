"""Pallas TPU megakernel for the lockstep tick.

The XLA tick (ops/tick.py) compiles to dozens of fusion islands, each round-tripping
the full (N, G) state through HBM; at 100k groups that HBM traffic, not compute, is
the throughput ceiling. This kernel runs the ENTIRE phase lattice (SEMANTICS.md
§9 phase F + §5 phases 0-5) for a tile of groups in one pallas_call: each state array
is read from HBM once, lives in VMEM across all phases, and is written back once.

Division of labor (bit-compatibility by construction):
- The phase logic is literally ops/tick.phase_body — the same function object the XLA
  tick runs; this module only changes where its inputs/outputs live.
- ALL randomness stays outside the kernel in ordinary XLA jax.random ops
  (ops/tick.make_aux / finish_tick): every draw phase_body needs is derivable from
  pre-tick state, except the deferred election draws, which the kernel reports back
  via an el_dirty output and finish_tick materializes. No threefry in Mosaic, no
  bit-replication risk.
- Bool state is passed to Mosaic as int32 (i1 memrefs are poorly supported) and
  converted at the kernel boundary.

The groups axis is the minor/lane axis of every array (models/state.py), so a tile is
a contiguous (…, tile_g) lane slab. tile_g defaults to the largest of 1024/512/256/128
dividing G; on TPU, G must be lane-aligned (pad_groups_for_pallas rounds a config up).
On CPU the kernel runs in interpreter mode automatically (tests), with any G.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from raft_kotlin_tpu.models.state import MAILBOX_FIELDS, RaftState
from raft_kotlin_tpu.ops import tick as tick_mod
from raft_kotlin_tpu.ops.tick import AUX_FIELDS, STATE_FIELDS, BodyFlags, state_fields
from raft_kotlin_tpu.utils.config import RaftConfig

_I32 = jnp.int32
# Bool<->int32 conversion happens only for (N, G) grids; pair-shaped fields
# (responded/link_up) and pair aux masks travel as int32 end to end — phase_body's
# contract (no i1 tensors at pair shape).
_BOOL_STATE = ("el_armed", "hb_armed", "up")
_BOOL_AUX = ("crash_m", "restart_m")
_TILES = (1024, 512, 256, 128)


def pick_tile(G: int, total_rows: int = 0) -> Optional[int]:
    """Largest supported tile dividing G that fits the Mosaic scoped-VMEM budget.

    Empirical cost model: the kernel's VMEM stack (inputs + outputs + live
    temporaries across the unrolled phase lattice) measures ~30 bytes per
    (row, lane) element — the N=5, C=32 config hits 34 MB at ~1120 rows x 1024
    lanes against the 16 MB scoped limit. Budget 12 MB for headroom.
    """
    budget = 12e6
    for t in _TILES:
        if G % t == 0 and (not total_rows or total_rows * t * 30 <= budget):
            return t
    return None


def choose_impl(cfg: RaftConfig) -> str:
    """Canonical backend auto-selection (Simulator, CLI, bench all use this):
    "pallas" when running on an accelerator AND the megakernel is buildable for
    cfg.n_groups (lane alignment + the VMEM tile model), else "xla". Both backends
    are bit-identical; this only picks the faster compilable one. Note Mosaic
    compiles lazily — a pathological config could still fail at the first step, in
    which case callers wanting hard guarantees should warm up and fall back
    (see bench.py measure())."""
    if jax.default_backend() == "cpu":
        return "xla"
    if cfg.uses_dyn_log:
        return "xla"  # dyn-log band: the batched XLA engine (ops/tick.py)
    try:
        default_tile(cfg, cfg.n_groups, interpret=False)
    except ValueError:
        return "xla"
    return "pallas"


def pad_groups_for_pallas(cfg: RaftConfig, tile: int = 256) -> RaftConfig:
    """Round n_groups up to a lane-aligned multiple (extra groups are real
    simulations, just surplus — same convention as parallel.mesh.pad_groups)."""
    g = ((cfg.n_groups + tile - 1) // tile) * tile
    return dataclasses.replace(cfg, n_groups=g)


def make_pallas_core(cfg: RaftConfig, lanes: int, tile_g: int, interpret: bool):
    """Per-flags builder of the raw megakernel over arrays with `lanes` lane columns
    (the flat phase_body layout). Used with lanes = n_groups for single-device runs
    (make_pallas_tick) and lanes = the per-device shard width under shard_map
    (parallel.mesh.make_sharded_run(impl="pallas")). Returns build_call(flags) ->
    (callable(*flat_int32_arrays) -> flat outputs + el_dirty, aux_names)."""
    N, C = cfg.n_nodes, cfg.log_capacity
    assert lanes % tile_g == 0, (lanes, tile_g)
    # Log blocks travel in the STORAGE dtype (cfg.log_dtype): int16 halves
    # the VMEM footprint and the VPU data movement of the dominant one-hot
    # log ops (Mosaic packs 16-bit lanes 2x). Everything else is int32.
    log_dt = jnp.int16 if cfg.log_dtype == "int16" else _I32

    # Per-tile block shapes. Everything is RANK-2 (rows, tile_g): phase_body's flat
    # layout (ops/tick.py) — pair grids (N*N, ·), logs (N*C, ·) — which is also what
    # Mosaic wants (no rank-3 i1 vectors, lane axis minor).
    field_shapes = {
        **{k: (N, tile_g) for k in STATE_FIELDS},
        "log_term": (N * C, tile_g), "log_cmd": (N * C, tile_g),
        "responded": (N * N, tile_g), "next_index": (N * N, tile_g),
        "match_index": (N * N, tile_g), "link_up": (N * N, tile_g),
        **{k: (N * N, tile_g) for k in MAILBOX_FIELDS},
    }
    aux_shapes = {
        "edge_iid": (N * N, tile_g), "crash_m": (N, tile_g),
        "restart_m": (N, tile_g), "link_fail": (N * N, tile_g),
        "link_heal": (N * N, tile_g), "el_draw_f": (N, tile_g),
        "bdraw": (N, tile_g), "periodic": (1, tile_g), "inject": (N, tile_g),
        "delay": (N * N, tile_g),
    }

    def block_spec(shape):
        return pl.BlockSpec(shape, lambda i: (0, i))

    @functools.lru_cache(maxsize=None)
    def build_call(flags: BodyFlags):
        # Mosaic has no gather/scatter in the TC path: always the one-hot form.
        flags = dataclasses.replace(flags, dyn_log=False, batched=False,
                                    sharded=False)
        sfields = state_fields(flags)
        aux_names = tuple(
            k for k in AUX_FIELDS
            if (k in ("edge_iid", "bdraw"))
            or (k in ("crash_m", "restart_m", "el_draw_f") and flags.faults)
            or (k in ("link_fail", "link_heal") and flags.links)
            or (k == "periodic" and flags.periodic)
            or (k == "inject" and flags.inject)
            or (k == "delay" and flags.delay and cfg.delay_lo < cfg.delay_hi)
        )

        def kernel(*refs):
            n_in = len(sfields) + len(aux_names)
            ins = dict(zip(sfields + aux_names, refs[:n_in]))
            outs = dict(zip(sfields + ("el_dirty",), refs[n_in:]))
            s = {}
            for k in sfields:
                v = ins[k][...]
                s[k] = (v != 0) if k in _BOOL_STATE else v
            aux = {}
            for k in aux_names:
                v = ins[k][...]
                aux[k] = (v != 0) if k in _BOOL_AUX else v
            el_dirty = tick_mod.phase_body(cfg, s, aux, flags)
            for k in sfields:
                outs[k][...] = s[k].astype(_I32) if k in _BOOL_STATE else s[k]
            outs["el_dirty"][...] = el_dirty.astype(_I32)

        def field_dtype(k):
            return log_dt if k in ("log_term", "log_cmd") else _I32

        in_specs = [block_spec(field_shapes[k]) for k in sfields]
        in_specs += [block_spec(aux_shapes[k]) for k in aux_names]
        out_shapes = [
            jax.ShapeDtypeStruct(
                tuple(field_shapes[k][:-1]) + (lanes,), field_dtype(k))
            for k in sfields
        ] + [jax.ShapeDtypeStruct((N, lanes), _I32)]
        out_specs = [block_spec(field_shapes[k]) for k in sfields]
        out_specs += [block_spec((N, tile_g))]

        call = pl.pallas_call(
            kernel,
            grid=(lanes // tile_g,),
            in_specs=in_specs,
            out_specs=out_specs,
            out_shape=out_shapes,
            input_output_aliases={i: i for i in range(len(sfields))},
            interpret=interpret,
        )
        return call, sfields, aux_names

    return build_call


def cast_aux_in(aux: dict, aux_names):
    """Order + int32-cast the aux kernel operands (the aux half of
    cast_flat_in; the flat-carry runner uses it alone — its state already
    rides in kernel form)."""
    return [aux[k].astype(_I32) if k in _BOOL_AUX else aux[k]
            for k in aux_names]


def cast_flat_in(flat: dict, aux: dict, sfields, aux_names):
    """Order + int32-cast the kernel operands from the flat state/aux dicts."""
    ins = []
    for k in sfields:
        v = flat[k]
        ins.append(v.astype(_I32) if k in _BOOL_STATE else v)
    return ins + cast_aux_in(aux, aux_names)


def cast_flat_out(outs, sfields):
    """Inverse of cast_flat_in for the kernel outputs -> (flat state dict, el_dirty)."""
    s = {}
    for k, v in zip(sfields, outs[: len(sfields)]):
        s[k] = (v != 0) if k in _BOOL_STATE else v
    return s, outs[-1] != 0


def make_pallas_tick(cfg: RaftConfig, tile_g: Optional[int] = None,
                     interpret: Optional[bool] = None):
    """Build tick(state, inject=None, fault_cmd=None[, rng]) -> state — same
    contract and same bits as ops.tick.make_tick(cfg), different compilation
    strategy."""
    N, C, G = cfg.n_nodes, cfg.log_capacity, cfg.n_groups
    default_rng: list = []  # derived lazily; wrappers always pass rng explicitly

    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    if tile_g is None:
        tile_g = default_tile(cfg, G, interpret)
    if interpret and G % tile_g:
        tile_g = G  # interpreter: one tile, no alignment constraints

    build_call = make_pallas_core(cfg, G, tile_g, interpret)

    def tick(
        state: RaftState,
        inject: Optional[jax.Array] = None,
        fault_cmd: Optional[jax.Array] = None,
        rng=None,
    ) -> RaftState:
        assert state.term.shape[-1] == G, (
            f"state has {state.term.shape[-1]} groups, kernel built for {G}"
        )
        if rng is None:
            if not default_rng:
                # Eager even under a jit trace (see ops.tick: a staged tracer
                # cached here would leak into later trace signatures).
                with jax.ensure_compile_time_eval():
                    default_rng.append(tick_mod.make_rng(cfg))
            rng = default_rng[0]
        base, tkeys, bkeys = rng
        aux, flags = tick_mod.make_aux(
            cfg, base, tkeys, bkeys, state, inject, fault_cmd)
        call, sfields, aux_names = build_call(flags)
        flat = tick_mod.flatten_state(cfg, state)
        outs = call(*cast_flat_in(flat, aux, sfields, aux_names))
        s, el_dirty = cast_flat_out(outs, sfields)
        return tick_mod.finish_tick(
            cfg, tkeys, tick_mod.unflatten_state(cfg, s), el_dirty, state.tick)

    return tick


def make_pallas_scan(cfg: RaftConfig, n_ticks: int,
                     tile_g: Optional[int] = None,
                     interpret: Optional[bool] = None):
    """Multi-tick Pallas runner with a FLAT int32 scan carry.

    Scanning make_pallas_tick converts RaftState <-> the kernel's flat int32
    layout EVERY tick (bool<->int32 casts, pair/log reshapes); the round-4
    profile attributes ~0.3 ms of the 2.3 ms headline tick to exactly those
    conversion fusions. Here the scan carries the flat kernel form and the
    conversions run once per CALL: flatten+cast before the scan, cast+
    unflatten after. Bits are identical by construction (same phase_body
    kernel, same aux draws, same deferred-draw materialization).

    Returns run(state, rng) -> state (jitted; rng rides as an operand so the
    compilation is seed-independent, as everywhere else)."""
    import types

    N, G = cfg.n_nodes, cfg.n_groups
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    if tile_g is None:
        tile_g = default_tile(cfg, G, interpret)
    if interpret and G % tile_g:
        tile_g = G
    build_call = make_pallas_core(cfg, G, tile_g, interpret)
    sfields = state_fields(tick_mod.make_flags(cfg))

    @jax.jit
    def run(state: RaftState, rng):
        base, tkeys, bkeys = rng
        flat = tick_mod.flatten_state(cfg, state)
        # One-time entry casts (the per-tick cost this runner removes).
        for k in _BOOL_STATE:
            flat[k] = flat[k].astype(_I32)

        def body(carry, _):
            s, t = carry
            shim = types.SimpleNamespace(
                tick=t, t_ctr=s["t_ctr"], b_ctr=s["b_ctr"])
            aux, flags = tick_mod.make_aux(
                cfg, base, tkeys, bkeys, shim, None, None)
            call, sfields, aux_names = build_call(flags)
            outs = call(*([s[k] for k in sfields] + cast_aux_in(aux, aux_names)))
            s2 = dict(zip(sfields, outs[:-1]))
            s2["el_left"] = tick_mod.materialize_el(
                cfg, tkeys, s2, outs[-1] != 0)
            return (s2, t + 1), None

        (flat, t), _ = jax.lax.scan(body, (flat, state.tick), None,
                                    length=n_ticks)
        s = {k: ((flat[k] != 0) if k in _BOOL_STATE else flat[k])
             for k in sfields}
        return RaftState(**tick_mod.unflatten_state(cfg, s), tick=t)

    return run


def default_tile(cfg: RaftConfig, lanes: int, interpret: bool) -> int:
    """VMEM-model tile choice for `lanes` lane columns (raises if none fits)."""
    N, C = cfg.n_nodes, cfg.log_capacity
    if interpret:
        return min(lanes, 256)
    # Rows across all in/out blocks: 2x state (in + aliased out) + worst-case aux
    # + el_dirty.
    n_2d = sum(1 for k in STATE_FIELDS
               if k not in ("log_term", "log_cmd", "responded",
                            "next_index", "match_index", "link_up"))
    log_rows = 2 * 2 * N * C  # 2 log arrays, in + aliased out
    if cfg.log_dtype == "int16":
        log_rows //= 2  # i16 rows cost half the VMEM of the i32 model rows
    rows = 2 * (n_2d * N + 4 * N * N) + log_rows + (3 * N * N + 5 * N + 1) + N
    if cfg.uses_mailbox:
        # §10 mailbox: 13 pair-shaped state fields (in + aliased out) + delay aux.
        rows += 2 * len(MAILBOX_FIELDS) * N * N + N * N
    t = pick_tile(lanes, rows)
    if t is None:
        if pick_tile(lanes) is None:
            raise ValueError(
                f"{lanes} lanes is not a multiple of any supported tile {_TILES}; "
                "pad with pad_groups_for_pallas()")
        raise ValueError(
            f"no tile in {_TILES} dividing {lanes} lanes fits the scoped-VMEM "
            f"budget for n_nodes={N}, log_capacity={C}; shrink the config or "
            "pass tile_g explicitly")
    return t
