"""Pallas TPU megakernel for the lockstep tick.

The XLA tick (ops/tick.py) compiles to dozens of fusion islands, each round-tripping
the full (N, G) state through HBM; at 100k groups that HBM traffic, not compute, is
the throughput ceiling. This kernel runs the ENTIRE phase lattice (SEMANTICS.md
§9 phase F + §5 phases 0-5) for a tile of groups in one pallas_call: each state array
is read from HBM once, lives in VMEM across all phases, and is written back once.

Division of labor (bit-compatibility by construction):
- The phase logic is literally ops/tick.phase_body — the same function object the XLA
  tick runs; this module only changes where its inputs/outputs live.
- Randomness has TWO routed sources (aux_source, a plan dimension since r17):
  "staged" keeps every draw outside the kernel in ordinary XLA jax.random ops
  (ops/tick.make_aux / finish_tick) — aux masks arrive as materialized HBM
  arrays the kernel re-reads; "inkernel" re-derives the SAME bits inside the
  kernel from (seed, tick, group) counters via utils/rng's kt_* threefry
  twins (SEMANTICS.md §17) — the aux HBM stream and its XLA pre-pass
  disappear, and only a few resident key/scenario rows cross the launch.
  ops/tick.make_aux stays the single semantic source; the twin is pinned
  bit-identical against it (tests/test_inkernel_aux.py), never forked. The
  deferred election draws are unchanged either way: the kernel reports
  el_dirty and finish_tick materializes (T=1), or the fused kernel
  materializes in-kernel.
- Bool state is passed to Mosaic as int32 (i1 memrefs are poorly supported) and
  converted at the kernel boundary.

The groups axis is the minor/lane axis of every array (models/state.py), so a tile is
a contiguous (…, tile_g) lane slab. tile_g defaults to the largest of 1024/512/256/128
dividing G; on TPU, G must be lane-aligned (pad_groups_for_pallas rounds a config up).
On CPU the kernel runs in interpreter mode automatically (tests), with any G.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from raft_kotlin_tpu.constants import LEADER
from raft_kotlin_tpu.models.state import (MAILBOX_FIELDS, NARROW16,
                                          SNAPSHOT_FIELDS, RaftState,
                                          pack_ctrl_words_i32,
                                          pack_peer_word_i32, popcount32,
                                          synth_vote_bits,
                                          unpack_ctrl_words_i32,
                                          unpack_peer_word_i32)
from raft_kotlin_tpu.ops import tick as tick_mod
from raft_kotlin_tpu.ops.tick import AUX_FIELDS, STATE_FIELDS, BodyFlags, state_fields
from raft_kotlin_tpu.utils import rng as rngmod
from raft_kotlin_tpu.utils.config import RaftConfig

_I32 = jnp.int32
_I16 = jnp.int16
# Bool<->int16 conversion happens only for (N, G) grids; pair-shaped fields
# (responded/link_up) and pair aux masks travel as int16 end to end — phase_body's
# contract (no i1 tensors at pair shape).
_BOOL_STATE = ("el_armed", "hb_armed", "up")
_BOOL_AUX = ("crash_m", "restart_m")
_TILES = (1024, 512, 256, 128)

# Packed-domain compute (SEMANTICS.md §18): under compute="packed" the
# kernel's HOT planes cross HBM (and live in VMEM) as packed i32 words —
# the nine hot fields below collapse to four word planes, and the phase
# lattice runs on the packed vote-exchange set directly
# (BodyFlags.packed_compute). Cold/wide fields (logs, terms, positions,
# the mailbox slots) keep the §14 unpack-at-read path; match_index stays
# wide because the r8 order-statistic commit sorts its full-width rows.
COMPUTES = ("unpacked", "packed")
HOT_FIELDS = ("role", "round_state", "el_armed", "hb_armed", "up",
              "votes", "responses", "responded", "link_up")
PACKED_WORD_FIELDS = ("ctrl_words", "responded_bits", "link_bits",
                      "vote_bits")


def packed_operand_fields(sfields) -> tuple:
    """The kernel operand field tuple under compute="packed": the hot
    planes replaced (in place, tail position) by the packed word planes —
    deterministic order shared by operand lists, output zips and the
    aliasing map."""
    return tuple(k for k in sfields if k not in HOT_FIELDS) \
        + PACKED_WORD_FIELDS


def packed_word_shape(k: str, N: int, lanes: int) -> tuple:
    """Block shape of a packed word plane: the ctrl stack is 3 words
    (role / round_state / el|hb|up flags), peer masks one word per node."""
    return (3 if k == "ctrl_words" else N, lanes)


def hot_plane_rows(cfg: RaftConfig, compute: str = "unpacked") -> int:
    """VMEM-model rows the HOT planes occupy per direction (the quantity
    the §18 acceptance ratio is stated over): 7 node fields + 2 pair
    planes unpacked; 3 ctrl words + 3 N-row word planes packed."""
    N = cfg.n_nodes
    if compute == "packed":
        return 3 + 3 * N
    return 7 * N + 2 * N * N


def flat_to_packed_compute(cfg: RaftConfig, s: dict) -> dict:
    """Flat i32 kernel-form dict -> the §18 packed operand dict: the nine
    HOT planes collapse to the four packed word planes (§14 bit layouts,
    models/state helpers). vote_bits is synthesized from (responded_bits,
    votes) — observationally equivalent, see synth_vote_bits."""
    N = cfg.n_nodes
    out = {k: v for k, v in s.items() if k not in HOT_FIELDS}
    out["ctrl_words"] = pack_ctrl_words_i32(
        s["role"], s["round_state"], s["el_armed"], s["hb_armed"], s["up"])
    rb = pack_peer_word_i32(s["responded"], N)
    out["responded_bits"] = rb
    out["link_bits"] = pack_peer_word_i32(s["link_up"], N)
    out["vote_bits"] = synth_vote_bits(rb, s["votes"], N)
    return out


def packed_compute_to_flat(cfg: RaftConfig, s: dict) -> dict:
    """Inverse of flat_to_packed_compute: restore the nine wide hot planes
    (votes/responses as popcounts — the §18 identity) in i32, the flat
    carry's dtype for every hot field."""
    N = cfg.n_nodes
    out = {k: v for k, v in s.items() if k not in PACKED_WORD_FIELDS}
    out.update(unpack_ctrl_words_i32(s["ctrl_words"], N))
    rb = s["responded_bits"].astype(_I32)
    out["responded"] = unpack_peer_word_i32(rb, N)
    out["link_up"] = unpack_peer_word_i32(s["link_bits"], N)
    out["votes"] = popcount32(s["vote_bits"].astype(_I32))
    out["responses"] = popcount32(rb)
    return out


def _enter_packed_lattice(cfg: RaftConfig, s: dict) -> dict:
    """Kernel-interior prologue (per slab, ONCE per launch): unpack the
    ctrl words and the link word to the wide planes phase_body reads —
    the in-lattice §18 set keeps ONLY responded_bits/vote_bits packed
    (the vote-exchange words phase_body evaluates directly under
    BodyFlags.packed_compute)."""
    N = cfg.n_nodes
    ctrl = unpack_ctrl_words_i32(s.pop("ctrl_words"), N)
    s["role"] = ctrl["role"]
    s["round_state"] = ctrl["round_state"]
    s["el_armed"] = ctrl["el_armed"] != 0
    s["hb_armed"] = ctrl["hb_armed"] != 0
    s["up"] = ctrl["up"] != 0
    s["link_up"] = unpack_peer_word_i32(s.pop("link_bits"), N)
    return s


def _exit_packed_lattice(cfg: RaftConfig, s: dict) -> dict:
    """Kernel-interior epilogue: repack the ctrl/link planes for the HBM
    store (responded_bits/vote_bits are already words in `s`)."""
    N = cfg.n_nodes
    out = dict(s)
    out["ctrl_words"] = pack_ctrl_words_i32(
        s["role"], s["round_state"], s["el_armed"], s["hb_armed"], s["up"])
    out["link_bits"] = pack_peer_word_i32(s["link_up"], N)
    for k in ("role", "round_state", "el_armed", "hb_armed", "up",
              "link_up"):
        del out[k]
    return out


def pick_tile(G: int, total_rows: int = 0) -> Optional[int]:
    """Largest supported tile dividing G that fits the Mosaic scoped-VMEM budget.

    Empirical cost model: the kernel's VMEM stack (inputs + outputs + live
    temporaries across the unrolled phase lattice) costs B bytes per
    (row, lane) element. The round-4 tile ladder on the headline config
    (N=5, C=32, 1156 model rows — scripts/probe_stage1_tiles.py) brackets B:
    Mosaic ACCEPTS tile 512 (=> B <= 27) and REJECTS tile 1024 (=> B > 13.5)
    against its ~16 MB scoped limit. B=20 with a 12 MB budget reproduces
    that boundary exactly (512 in, 1024 out) and is re-validated both ways
    by tests/test_tpu_pallas.py::test_tile_rejection_boundary.
    """
    budget = 12e6
    for t in _TILES:
        if G % t == 0 and (not total_rows or total_rows * t * 20 <= budget):
            return t
    return None


# ---------------------------------------------------------------------------
# Sub-tile ILP + fused-tick routing (ISSUE 4 / ISSUE 7). The phase lattice
# is a ~240-op serial dependency chain per lane and the headline kernel
# sits ~5x under both the HBM and VPU rooflines (BENCH_r05) — issue+launch
# latency, not bandwidth or slots, is the binding resource. K independent
# lane slabs per tile overlap K chains inside one kernel body (the win
# saturates at the 128-lane vreg floor); T full phase lattices per launch
# amortize the launch and keep state VMEM-resident between ticks. K=1/T=1
# keep the pre-ILP/pre-fusion kernel byte-identical and are the sticky
# CPU/interpret guards.
#
# Since round 13 the measured crossover data lives in the UNIFIED tuning
# table (parallel/autotune.py — one plan layer for engine + ILP + fused +
# sharding, measure-on-first-use, pinnable via scripts/autotune.py).
# ILP_SUBTILE_TABLE / FUSED_TICK_TABLE remain as DERIVED VIEWS (the same
# (tile, K|T, source) tuples the historical tests and probes read) and
# the route_* functions delegate; tests/test_autotune.py pins the views
# equal to the unified layer over the full tile lattice.
from raft_kotlin_tpu.parallel import autotune as autotune_mod

ILP_SUBTILE_TABLE = autotune_mod.derived_ilp_table()
FUSED_TICK_TABLE = autotune_mod.derived_fused_table()


def route_ilp_subtiles(tile_g: int, platform: Optional[str] = None) -> int:
    """Sub-tile count K for a megakernel tile of `tile_g` lanes, from the
    unified tuning table. CPU guard: the interpreter executes ops serially
    — no issue latency to hide — and K multiplies trace size, so
    interpret/CPU runs stay at K=1 (tests pin K explicitly when they want
    the sub-tiled program on CPU). Unknown tiles (interpreter-only shapes)
    fall back to K=1; hardware tiles are exactly the _TILES ladder, all
    tabulated."""
    return autotune_mod.ilp_subtiles(tile_g, platform=platform)


def route_fused_ticks(tile_g: int, platform: Optional[str] = None) -> int:
    """Fused tick count T for a megakernel tile of `tile_g` lanes, from the
    unified tuning table. CPU guard: the interpreter pays no launch/issue
    latency to amortize, and T multiplies trace size, so interpret/CPU runs
    stay at T=1 (tests pin T explicitly when they want the fused program on
    CPU). Unknown tiles fall back to T=1 — the byte-identical pre-fusion
    path."""
    return autotune_mod.fused_ticks(tile_g, platform=platform)


# Per-tick observables the fused kernel can snapshot (post-tick, one output
# block per (field, tick)): the union the flight recorder, the safety
# monitor, and the differential trace surface read between launches.
FUSED_TRACE_FIELDS = ("role", "term", "commit", "last_index")


def fused_snapshot_fields(cfg: RaftConfig, telemetry: bool = False,
                          monitor: bool = False, trace: bool = False,
                          serving: bool = False) -> tuple:
    """The ordered state-field set a fused launch must snapshot per tick so
    the requested observers (recorder / monitor / differential trace) can
    replay the T per-tick transitions between launches. Ordered canonically
    (STATE_FIELDS then mailbox) so kernel output lists are deterministic."""
    from raft_kotlin_tpu.utils.telemetry import (
        MONITOR_COMPACT_FIELDS, MONITOR_STATE_FIELDS,
        TELEMETRY_COMPACT_FIELDS, TELEMETRY_MAILBOX_FIELDS,
        TELEMETRY_STATE_FIELDS)

    want = []
    if trace:
        want += list(FUSED_TRACE_FIELDS)
    if telemetry:
        want += list(TELEMETRY_STATE_FIELDS)
        if cfg.uses_compaction:
            want += list(TELEMETRY_COMPACT_FIELDS)
    if monitor:
        want += list(MONITOR_STATE_FIELDS)
        if cfg.uses_compaction:
            want += list(MONITOR_COMPACT_FIELDS)
        if getattr(cfg, "uses_ops_plane", False):
            # §21: the series/event channels also read election rounds —
            # snapshot it so fused replay can fill them (the telemetry
            # set already carries it; monitor-only needs it added).
            want += ["rounds"]
    if serving:
        # §20: a strict subset of the monitor's set (the serving step's
        # replay reads role/up/commit/hb_armed/log_cmd + the §15 snapshot
        # planes), so serving+monitor costs no extra snapshot rows.
        from raft_kotlin_tpu.ops.serving import (
            SERVING_COMPACT_FIELDS, SERVING_STATE_FIELDS)

        want += list(SERVING_STATE_FIELDS)
        if cfg.uses_compaction:
            want += list(SERVING_COMPACT_FIELDS)
    if (telemetry or monitor) and cfg.uses_mailbox:
        want += list(TELEMETRY_MAILBOX_FIELDS)
    order = {k: i for i, k in enumerate(
        STATE_FIELDS + MAILBOX_FIELDS + SNAPSHOT_FIELDS)}
    return tuple(sorted(set(want), key=order.__getitem__))


def _snapshot_rows(cfg: RaftConfig, fields) -> int:
    """Model rows one tick's snapshot output set occupies (VMEM model)."""
    N, C = cfg.n_nodes, cfg.phys_capacity
    pair = ("responded", "next_index", "match_index",
            "link_up") + MAILBOX_FIELDS
    r = 0
    for k in fields:
        if k in ("log_term", "log_cmd"):
            r += N * C
        elif k in pair:
            r += N * N
        else:
            r += N
    return r


def choose_impl(cfg: RaftConfig) -> str:
    """Canonical backend auto-selection (Simulator, CLI, bench all use this):
    "pallas" when running on an accelerator AND the megakernel is buildable for
    cfg.n_groups (lane alignment + the VMEM tile model), else "xla". Both backends
    are bit-identical; this only picks the faster compilable one. Note Mosaic
    compiles lazily — a pathological config could still fail at the first step, in
    which case callers wanting hard guarantees should warm up and fall back
    (see bench.py measure())."""
    if jax.default_backend() == "cpu":
        return "xla"
    if cfg.uses_dyn_log:
        return "xla"  # dyn-log band: the batched XLA engine (ops/tick.py)
    if cfg.uses_compaction:
        return "xla"  # §15 ring translate: CPU-interpret-proven, no
        #                hardware artifact yet (plan_for's shallow guard)
    try:
        default_tile(cfg, cfg.n_groups, interpret=False)
    except ValueError:
        return "xla"
    return "pallas"


def pad_groups_for_pallas(cfg: RaftConfig, tile: int = 256) -> RaftConfig:
    """Round n_groups up to a lane-aligned multiple (extra groups are real
    simulations, just surplus — same convention as parallel.mesh.pad_groups)."""
    g = ((cfg.n_groups + tile - 1) // tile) * tile
    return dataclasses.replace(cfg, n_groups=g)


def kernel_field_dtype(cfg: RaftConfig, k: str):
    """Dtype of a state field in the flat KERNEL form: the log storage dtype
    for logs, int32 for EVERYTHING else — including the int16-stored NARROW16
    fields and bool fields (i32 stand-ins). Narrow state blocks in the
    megakernel trip a Mosaic layout bug (layout.h \"arr.size() >=
    layout_rank\" SIGABRT once phase 3's columnar view is included; minimal
    i16-block/bool-cast/1-D-i16 repros all pass, so it is an interaction bug
    — round-4 bisection via RAFT_PHASE_CUT). State therefore crosses the
    kernel boundary widened; the storage narrowing still pays on the XLA
    paths (deep engine, sharded shard_map) and on checkpoints. Aux blocks
    are inputs only (no aliasing constraint) and DO ride int16."""
    if k in ("log_term", "log_cmd"):
        return _I16 if cfg.log_dtype == "int16" else _I32
    return _I32


# ---------------------------------------------------------------------------
# In-kernel aux generation (ISSUE 15, SEMANTICS.md §17): aux_source =
# "inkernel" deletes the staged aux HBM stream — the kernel re-derives every
# per-tick mask/draw from a few RESIDENT rows (base-key words, launch tick,
# global group index, the ScenarioBank's per-group (G,) rows) and the
# tkeys/bkeys key-word planes, via utils/rng's kt_* threefry twins. The
# host packers below build those operands; _kt_aux is the kernel-side twin
# of ops/tick.make_aux (same channel presence rules, same fast paths, same
# integer-exact compares — pinned bit-identical, never forked).

AUX_SOURCES = ("staged", "inkernel")


def inkernel_table_rows(cfg: RaftConfig) -> int:
    """Rows of the resident i32 key table: [k0; k1; tick0; gidx] + one row
    per active ScenarioBank channel (rng.scen_layout)."""
    return 4 + len(rngmod.scen_layout(cfg))


def reject_timeout_windows(cfg: RaftConfig) -> None:
    """Per-group election-timeout windows (§19 scenario.timeout_windows)
    are XLA-engine-only for now: every Pallas el-draw site (boot tables,
    phase-F redraw, deferred §7 materialization) bakes the scalar
    cfg.el_lo/el_hi window, so running such a bank here would silently
    draw the wrong bits. The kernel-twin draw primitives already take
    array bounds (kt_draw_uniform/kt_randint — bit-pinned in
    tests/test_scheduler.py), so lighting this up is plumbing, not math."""
    if cfg.scenario is not None and cfg.scenario.timeout_windows:
        raise NotImplementedError(
            "scenario.timeout_windows (§19) is not wired into the Pallas "
            "engines yet — run the XLA engine (the continuous farm path)")


def inkernel_aux_statics(cfg: RaftConfig, base, tkeys, bkeys, scen) -> dict:
    """The launch-invariant halves of the inkernel operands, computed ONCE
    per run from the rng operand (trivial bitcasts/stacks — runtime values,
    so compilations stay seed-independent): the key-table head (base-key
    words) and tail (global group-index iota + scenario rows), plus the
    (2N, G) timeout/backoff key-word planes."""
    G = cfg.n_groups
    scen = scen or {}
    scen_keys = rngmod.scen_layout(cfg)
    b0, b1 = rngmod.kt_key_words(base)
    head = jnp.stack([jnp.broadcast_to(b0.astype(_I32), (G,)),
                      jnp.broadcast_to(b1.astype(_I32), (G,))])
    tail = jnp.stack([jnp.arange(G, dtype=_I32)]
                     + [scen[nm].astype(_I32) for nm in scen_keys])
    t0, t1 = rngmod.kt_key_words(tkeys)
    u0, u1 = rngmod.kt_key_words(bkeys)
    return {"head": head, "tail": tail,
            "tkw": jnp.concatenate([t0, t1], axis=0),
            "bkw": jnp.concatenate([u0, u1], axis=0)}


def inkernel_aux_operands(stat: dict, tick0) -> list:
    """The inkernel launch operands [ktab, tkw, bkw] at launch tick `tick0`
    (the one per-launch row — a broadcast, not a draw: no XLA aux pre-pass
    remains on the hot path)."""
    G = stat["head"].shape[-1]
    row = jnp.broadcast_to(jnp.asarray(tick0, _I32), (1, G))
    return [jnp.concatenate([stat["head"], row, stat["tail"]], axis=0),
            stat["tkw"], stat["bkw"]]


def _kt_consts(cfg: RaftConfig, scen_keys: tuple, ktab, tkw, bkw) -> dict:
    """Per-slab launch constants, unpacked INSIDE the kernel from the
    resident operands: lane-uniform base-key word rows, the launch tick,
    per-lane linear lattice indices (the row-major counters the host's
    shaped draws consume: pair element [p, g] sits at g*N*N + p, node
    element [n, g] at g*N + n), sender/receiver ids, and the scenario
    rows keyed by rng.scen_layout order."""
    N = cfg.n_nodes
    L = ktab.shape[-1]
    gidx = ktab[3:4]
    p_col = jax.lax.broadcasted_iota(_I32, (N * N, 1), 0)
    return {
        "k0": ktab[0:1], "k1": ktab[1:2], "tick0": ktab[2:3],
        "scen": {nm: ktab[4 + i:5 + i] for i, nm in enumerate(scen_keys)},
        "idx_pair": gidx * (N * N)
        + jax.lax.broadcasted_iota(_I32, (N * N, L), 0),
        "idx_node": gidx * N + jax.lax.broadcasted_iota(_I32, (N, L), 0),
        "s_id": p_col // N + 1, "r_id": p_col % N + 1,
        "n_col": jax.lax.broadcasted_iota(_I32, (N, 1), 0),
        "tk0": tkw[:N], "tk1": tkw[N:], "bk0": bkw[:N], "bk1": bkw[N:],
    }


def _kt_thresh(cfg: RaftConfig, scen: dict, row: str, scalar: str):
    """A channel's 23-bit threshold: the scenario row when the bank carries
    it, else the config scalar through p_threshold, else None (the
    all-constant fast path) — exactly make_aux's precedence."""
    if row in scen:
        return scen[row]
    p = getattr(cfg, scalar)
    return rngmod.p_threshold(p) if p > 0 else None


def _kt_aux(cfg: RaftConfig, flags: BodyFlags, kt: dict, s: dict, t: int):
    """One tick's aux dict computed INSIDE the kernel — the kernel twin of
    ops/tick.make_aux over the same channel set flags select, at launch
    tick + t. Scripted partitions evaluate from the LIVE VMEM role/up
    planes (at each fused tick start these equal the staged path's
    pre-tick state — the evaluation that lifts the fused leader-iso
    fallback). Channel dtypes match the staged kernel load path: bool for
    _BOOL_AUX, int32 elsewhere."""
    N = cfg.n_nodes
    L = kt["k0"].shape[-1]
    k0, k1, scen = kt["k0"], kt["k1"], kt["scen"]
    tick = kt["tick0"] + t
    aux = {}
    if flags.delay and cfg.delay_lo < cfg.delay_hi:
        lo = scen.get("delay_lo", cfg.delay_lo)
        hi = scen.get("delay_hi", cfg.delay_hi)
        aux["delay"] = rngmod.kt_delay_mask(k0, k1, tick, kt["idx_pair"],
                                            lo, hi)
    et = _kt_thresh(cfg, scen, "drop_t", "p_drop")
    if et is None:
        edge = jnp.ones((N * N, L), bool)
    else:
        edge = rngmod.kt_edge_ok_mask(k0, k1, tick, kt["idx_pair"], et)
    if "part_kind" in scen:
        lead_s, lead_r = None, None
        if cfg.scenario is not None and cfg.scenario.needs_state:
            lead = (s["role"] == LEADER) & s["up"]  # (N, L) live planes
            lead_s = jnp.zeros((N * N, L), bool)
            lead_r = jnp.zeros((N * N, L), bool)
            for n in range(N):
                lead_s = lead_s | ((kt["s_id"] == n + 1) & lead[n:n + 1])
                lead_r = lead_r | ((kt["r_id"] == n + 1) & lead[n:n + 1])
        down = rngmod.kt_part_down(
            scen["part_kind"], scen["part_cut"], scen["part_src"],
            scen["part_dst"], rngmod.scenario_active(scen, tick),
            kt["s_id"], kt["r_id"], lead_s, lead_r)
        edge = edge & ~down
    aux["edge_iid"] = edge.astype(_I32)
    if flags.faults:
        ct = _kt_thresh(cfg, scen, "crash_t", "p_crash")
        rt = _kt_thresh(cfg, scen, "restart_t", "p_restart")
        crash = (jnp.zeros((N, L), bool) if ct is None else
                 rngmod.kt_event_mask(k0, k1, rngmod.KIND_CRASH, tick,
                                      kt["idx_node"], ct))
        restart = (jnp.zeros((N, L), bool) if rt is None else
                   rngmod.kt_event_mask(k0, k1, rngmod.KIND_RESTART, tick,
                                        kt["idx_node"], rt))
        W = 0 if cfg.scenario is None else cfg.scenario.warmup_down
        if W:
            # §15 warmup-down on the kernel (N, L) orientation — the same
            # rule as rng.apply_warmup_faults on the transposed lattice.
            notcmd = kt["n_col"] != (cfg.cmd_node - 1)
            hold = (tick < W) & notcmd
            crash = crash | hold
            restart = (restart & ~hold) | ((tick == W) & notcmd)
        aux["crash_m"], aux["restart_m"] = crash, restart
        aux["el_draw_f"] = rngmod.kt_draw_uniform(
            kt["tk0"], kt["tk1"], s["t_ctr"], cfg.el_lo, cfg.el_hi)
    if flags.links:
        ft = _kt_thresh(cfg, scen, "link_fail_t", "p_link_fail")
        ht = _kt_thresh(cfg, scen, "link_heal_t", "p_link_heal")
        aux["link_fail"] = (
            jnp.zeros((N * N, L), _I32) if ft is None else
            rngmod.kt_event_mask(k0, k1, rngmod.KIND_LINK_FAIL, tick,
                                 kt["idx_pair"], ft).astype(_I32))
        aux["link_heal"] = (
            jnp.zeros((N * N, L), _I32) if ht is None else
            rngmod.kt_event_mask(k0, k1, rngmod.KIND_LINK_HEAL, tick,
                                 kt["idx_pair"], ht).astype(_I32))
    aux["bdraw"] = rngmod.kt_draw_uniform(
        kt["bk0"], kt["bk1"], s["b_ctr"], cfg.bo_lo, cfg.bo_hi)
    if flags.periodic:
        due = ((tick % cfg.cmd_period) == 0) & (tick > 0)
        aux["periodic"] = jnp.where(due, tick, -jnp.ones_like(tick))
    return aux


def make_pallas_core(cfg: RaftConfig, lanes: int, tile_g: int, interpret: bool,
                     subtiles: int = 1, fused_ticks: int = 1,
                     resets_bound: Optional[int] = None,
                     tick_states: tuple = (), aux_source: str = "staged",
                     compute: str = "unpacked"):
    """Per-flags builder of the raw megakernel over arrays with `lanes` lane columns
    (the flat phase_body layout). Used with lanes = n_groups for single-device runs
    (make_pallas_tick) and lanes = the per-device shard width under shard_map
    (parallel.mesh.make_sharded_run(impl="pallas")). Returns build_call(flags) ->
    (callable(*flat_int32_arrays) -> flat outputs + el_dirty, aux_names).

    `fused_ticks` = T > 1 builds the FUSED-T engine instead (ISSUE 7): T
    full phase lattices per launch with state VMEM-resident between ticks,
    composed with the sub-tile ILP (K slabs x T ticks per launch), counted
    draws via per-launch tables, el_left materialized in-kernel, and an
    overflow output replacing el_dirty — see _make_fused_core for the
    contract (build_call then returns a 4-tuple ending in the snapshot
    field names). T=1 ignores `resets_bound`/`tick_states` and compiles the
    byte-identical pre-fusion kernel below.

    `subtiles` = K > 1 runs SUB-TILE ILP (ISSUE 4): the kernel interior
    splits each loaded (rows, tile_g) block into K contiguous lane slabs and
    runs the phase lattice on each slab as an INDEPENDENT chain — groups are
    embarrassingly independent, so the K copies of the ~240-op serial
    dependency chain (opcount.phase_body_chain_depth) carry no edges between
    them and the scheduler can interleave their issue, hiding the per-chain
    op latency up to K-fold. Bit-exact by construction: every phase_body op
    is elementwise over lanes (reductions run over rows), so which lanes
    share an op never changes any lane's value. HBM blocks, loads and
    stores are IDENTICAL to the K=1 kernel (one load + one store per array;
    the split is on loaded values, re-concatenated before the store), so
    the VMEM tile model is unchanged. K must divide tile_g; on hardware the
    sub-slab must stay lane-register aligned (tile_g/K a multiple of 128 —
    route_ilp_subtiles enforces this; tests pass arbitrary K in interpret
    mode).

    `aux_source` = "inkernel" (ISSUE 15, §17) drops the staged aux operands
    entirely: the kernel's inputs become state + the three RESIDENT planes
    [ktab (inkernel_table_rows, lanes), tkw (2N, lanes), bkw (2N, lanes)]
    and every aux channel is re-derived INSIDE the kernel by _kt_aux from
    the utils/rng kt_* twins — bit-identical to the staged draws by the
    §17 pins. build_call still returns (call, sfields, aux_names); the
    aux_names tuple stays the CHANNEL set (introspection), but callers
    assemble operands per aux_source (inkernel_aux_operands).

    `compute` = "packed" (ISSUE 16, §18) swaps the nine HOT operand
    planes for the four packed word planes (packed_operand_fields): the
    state crosses HBM packed, the ctrl/link words unpack ONCE per launch
    inside the kernel, and phase_body runs with
    BodyFlags.packed_compute=True — the vote-exchange set
    (responded_bits/vote_bits) is evaluated as popcount-compare words,
    never widened. build_call's returned field tuple is then the packed
    OPERAND ordering (callers zip against it)."""
    if aux_source not in AUX_SOURCES:
        raise ValueError(f"unknown aux_source {aux_source!r}")
    if compute not in COMPUTES:
        raise ValueError(f"unknown compute {compute!r}")
    if fused_ticks > 1:
        return _make_fused_core(cfg, lanes, tile_g, interpret, subtiles,
                                fused_ticks, resets_bound, tick_states,
                                aux_source=aux_source, compute=compute)
    pc = compute == "packed"
    inkernel = aux_source == "inkernel"
    scen_keys = rngmod.scen_layout(cfg) if inkernel else ()
    N, C = cfg.n_nodes, cfg.phys_capacity
    assert lanes % tile_g == 0, (lanes, tile_g)
    SUB = max(1, subtiles)
    assert tile_g % SUB == 0, (tile_g, subtiles)
    if not interpret and SUB > 1:
        assert (tile_g // SUB) % 128 == 0, (
            f"sub-tile width {tile_g // SUB} must be a multiple of the "
            f"128-lane vreg on hardware (tile_g={tile_g}, K={SUB})")
    sub_w = tile_g // SUB
    # Log blocks travel in the STORAGE dtype (cfg.log_dtype): int16 halves
    # the VMEM footprint and the VPU data movement of the dominant one-hot
    # log ops (Mosaic packs 16-bit lanes 2x). Everything else is int32.
    log_dt = jnp.int16 if cfg.log_dtype == "int16" else _I32

    # Per-tile block shapes. Everything is RANK-2 (rows, tile_g): phase_body's flat
    # layout (ops/tick.py) — pair grids (N*N, ·), logs (N*C, ·) — which is also what
    # Mosaic wants (no rank-3 i1 vectors, lane axis minor).
    field_shapes = {
        **{k: (N, tile_g) for k in STATE_FIELDS},
        "log_term": (N * C, tile_g), "log_cmd": (N * C, tile_g),
        "responded": (N * N, tile_g), "next_index": (N * N, tile_g),
        "match_index": (N * N, tile_g), "link_up": (N * N, tile_g),
        **{k: (N * N, tile_g) for k in MAILBOX_FIELDS},
        **{k: (N, tile_g) for k in SNAPSHOT_FIELDS},
        **{k: packed_word_shape(k, N, tile_g) for k in PACKED_WORD_FIELDS},
    }
    aux_shapes = {
        "edge_iid": (N * N, tile_g), "crash_m": (N, tile_g),
        "restart_m": (N, tile_g), "link_fail": (N * N, tile_g),
        "link_heal": (N * N, tile_g), "el_draw_f": (N, tile_g),
        "bdraw": (N, tile_g), "periodic": (1, tile_g), "inject": (N, tile_g),
        "delay": (N * N, tile_g),
    }

    def block_spec(shape):
        return pl.BlockSpec(shape, lambda i: (0, i))

    @functools.lru_cache(maxsize=None)
    def build_call(flags: BodyFlags):
        # Mosaic has no gather/scatter in the TC path: always the one-hot form.
        flags = dataclasses.replace(flags, dyn_log=False, batched=False,
                                    sharded=False)
        sfields = state_fields(flags)
        cfields = packed_operand_fields(sfields) if pc else sfields
        bflags = dataclasses.replace(flags, packed_compute=True) if pc \
            else flags
        aux_names = tuple(
            k for k in AUX_FIELDS
            if (k in ("edge_iid", "bdraw"))
            or (k in ("crash_m", "restart_m", "el_draw_f") and flags.faults)
            or (k in ("link_fail", "link_heal") and flags.links)
            or (k == "periodic" and flags.periodic)
            or (k == "inject" and flags.inject)
            or (k == "delay" and flags.delay and cfg.delay_lo < cfg.delay_hi)
        )
        if inkernel and flags.inject:
            raise ValueError(
                "aux_source='inkernel' has no inject channel: per-tick "
                "driver inputs are a staged-aux (T=1 fallback) surface")
        n_aux_in = 3 if inkernel else len(aux_names)

        def kernel(*refs):
            n_in = len(cfields) + n_aux_in
            ins = dict(zip(cfields, refs[:len(cfields)]))
            if not inkernel:
                ins.update(zip(aux_names, refs[len(cfields):n_in]))
            else:
                kt_loads = [r[...] for r in refs[len(cfields):n_in]]
            outs = dict(zip(cfields + ("el_dirty",), refs[n_in:]))
            # Blocks cross HBM in the narrow storage dtypes (the round-4 DMA
            # win); the kernel INTERIOR widens to int32 — Mosaic's int16
            # layout handling crashes on the columnar (G,) rows (layout.h
            # "arr.size() >= layout_rank" check), and int16 compute measured
            # no faster anyway (probe_headline_dtypes). Logs keep their
            # storage dtype: their (C, tile) one-hot ops are rank-2 and the
            # int16 log kernel is TPU-proven (TPU_PALLAS variant_int16_logs).
            loaded = {k: ins[k][...] for k in ins}
            parts = {k: [] for k in cfields}
            el_parts = []
            for kk in range(SUB):
                # SUB independent lane slabs, SUB independent phase-lattice
                # chains (no dataflow edges between iterations) — the
                # sub-tile ILP. SUB == 1 skips the value slicing entirely
                # (byte-identical program to the pre-ILP kernel).
                def slab(v):
                    return v if SUB == 1 else \
                        v[:, kk * sub_w:(kk + 1) * sub_w]
                s = {}
                for k in cfields:
                    v = slab(loaded[k])
                    if k in _BOOL_STATE:
                        s[k] = v != 0
                    elif k in ("log_term", "log_cmd"):
                        s[k] = v
                    else:
                        s[k] = v.astype(_I32)
                if pc:
                    # ctrl/link words unpack ONCE per launch; the
                    # vote-exchange words stay packed through phase_body.
                    s = _enter_packed_lattice(cfg, s)
                if inkernel:
                    kt = _kt_consts(cfg, scen_keys,
                                    *(slab(v) for v in kt_loads))
                    aux = _kt_aux(cfg, flags, kt, s, 0)
                else:
                    aux = {}
                    for k in aux_names:
                        v = slab(loaded[k])
                        aux[k] = (v != 0) if k in _BOOL_AUX \
                            else v.astype(_I32)
                el_dirty = tick_mod.phase_body(cfg, s, aux, bflags)
                if pc:
                    s = _exit_packed_lattice(cfg, s)
                for k in cfields:
                    parts[k].append(
                        s[k] if k in ("log_term", "log_cmd")
                        else s[k].astype(kernel_field_dtype(cfg, k)))
                el_parts.append(el_dirty.astype(_I32))

            def join(ps):
                return ps[0] if SUB == 1 else jnp.concatenate(ps, axis=1)

            for k in cfields:
                outs[k][...] = join(parts[k])
            outs["el_dirty"][...] = join(el_parts)

        def field_dtype(k):
            return kernel_field_dtype(cfg, k)

        in_specs = [block_spec(field_shapes[k]) for k in cfields]
        if inkernel:
            in_specs += [block_spec((4 + len(scen_keys), tile_g)),
                         block_spec((2 * N, tile_g)),
                         block_spec((2 * N, tile_g))]
        else:
            in_specs += [block_spec(aux_shapes[k]) for k in aux_names]
        out_shapes = [
            jax.ShapeDtypeStruct(
                tuple(field_shapes[k][:-1]) + (lanes,), field_dtype(k))
            for k in cfields
        ] + [jax.ShapeDtypeStruct((N, lanes), _I32)]
        out_specs = [block_spec(field_shapes[k]) for k in cfields]
        out_specs += [block_spec((N, tile_g))]  # el_dirty (i16)

        call = pl.pallas_call(
            kernel,
            grid=(lanes // tile_g,),
            in_specs=in_specs,
            out_specs=out_specs,
            out_shape=out_shapes,
            input_output_aliases={i: i for i in range(len(cfields))},
            interpret=interpret,
        )
        return call, cfields, aux_names

    return build_call


def _make_fused_core(cfg: RaftConfig, lanes: int, tile_g: int,
                     interpret: bool, subtiles: int, T: int,
                     resets_bound: Optional[int], tick_states: tuple,
                     aux_source: str = "staged",
                     compute: str = "unpacked"):
    """The fused-T megakernel builder (ISSUE 7): T full phase lattices per
    pallas_call with state resident in VMEM between ticks — HBM load once,
    store once per T-block — composed with the sub-tile ILP: each of the K
    lane slabs runs its own T-tick chain, so the launch overlaps K
    independent (T x chain)-deep dependency chains. This is the round-5
    K-tick kernel (make_pallas_core_k, kept below as the archived negative
    result) revived with what it was missing: ILP composition, snapshot
    outputs for the PR-5/6 observability harness, and measured routing.

    Randomness stays outside, exactly as in the archival kernel (the
    bit-compat invariant): per-tick aux masks arrive T-stacked, and the
    counter-keyed draws (election timeout, backoff) arrive as pre-drawn
    TABLES over the counter windows the launch can reach (draw_tables);
    the kernel one-hot-selects entries, so every draw equals the per-tick
    path's draw at the same counter bit for bit. el_left is materialized
    in-kernel at each tick boundary (same §7 formula as
    tick.materialize_el). Offsets past the table window are clamped and
    COUNTED into the (N, lanes) overflow output — the caller must discard
    the launch on any nonzero count (make_pallas_scan raises; the
    jitted=False embedding surfaces it through the flight recorder).

    `tick_states` is the tuple of state fields to snapshot POST-TICK for
    every fused tick, one output block per (field, tick) — the channel
    through which the flight recorder, the safety monitor, and the
    differential trace surface observe the T per-tick transitions between
    launches without touching phase_body (fused_snapshot_fields picks the
    set). Snapshots are plain stored outputs in the kernel compute dtypes
    (int32; logs in storage dtype).

    build_call(flags) -> (call, sfields, aux_names, snap_fields); call
    takes [state..., aux T-slabs..., el_table (N*W, lanes), b_table
    (N*T, lanes)] and returns state fields (aliased), the overflow count,
    then T * len(snap_fields) snapshot blocks (tick-major).

    `aux_source` = "inkernel" (ISSUE 15, §17) REPLACES the T-stacked aux
    slabs AND both draw tables with the three resident planes [ktab, tkw,
    bkw]: every per-tick channel is re-derived inside the T-loop by
    _kt_aux at launch tick + t, the counted el/backoff draws come from
    kt_draw_uniform at the LIVE counters (no table window, so no overflow
    is possible — the overflow output is kept, always zero, preserving
    the unpack/checked contract), el_left is re-drawn at t_ctr - 1, and
    scripted partitions read the CURRENT tick's pre-phase role/up planes —
    which is why leader-isolation banks fuse only on this path
    (resolve_fused_geometry lifts the sticky T->1 gate).

    `compute` = "packed" (ISSUE 16, §18): the HOT planes cross HBM as the
    four packed word planes and the vote-exchange words stay packed
    across ALL T fused lattices — the ctrl/link unpack and the terminal
    repack happen once per launch, not once per tick. Snapshots remain
    the wide per-tick planes ("votes" is derived by popcount at each
    snapshot point), so the observability surface is unchanged."""
    pc = compute == "packed"
    inkernel = aux_source == "inkernel"
    scen_keys = rngmod.scen_layout(cfg) if inkernel else ()
    N, C = cfg.n_nodes, cfg.phys_capacity
    assert lanes % tile_g == 0, (lanes, tile_g)
    SUB = max(1, subtiles)
    assert tile_g % SUB == 0, (tile_g, subtiles)
    if not interpret and SUB > 1:
        assert (tile_g // SUB) % 128 == 0, (
            f"sub-tile width {tile_g // SUB} must be a multiple of the "
            f"128-lane vreg on hardware (tile_g={tile_g}, K={SUB})")
    sub_w = tile_g // SUB
    if resets_bound is None:
        resets_bound = resets_per_tick_bound(
            N, cfg.uses_mailbox and cfg.delay_lo == 0)
    W = resets_bound * T

    field_shapes = {
        **{k: (N, tile_g) for k in STATE_FIELDS},
        "log_term": (N * C, tile_g), "log_cmd": (N * C, tile_g),
        "responded": (N * N, tile_g), "next_index": (N * N, tile_g),
        "match_index": (N * N, tile_g), "link_up": (N * N, tile_g),
        **{k: (N * N, tile_g) for k in MAILBOX_FIELDS},
        **{k: (N, tile_g) for k in SNAPSHOT_FIELDS},
        **{k: packed_word_shape(k, N, tile_g) for k in PACKED_WORD_FIELDS},
    }
    aux_rows = {
        "edge_iid": N * N, "crash_m": N, "restart_m": N, "link_fail": N * N,
        "link_heal": N * N, "periodic": 1, "delay": N * N,
    }

    def block_spec(shape):
        return pl.BlockSpec(shape, lambda i: (0, i))

    @functools.lru_cache(maxsize=None)
    def build_call(flags: BodyFlags):
        flags = dataclasses.replace(flags, dyn_log=False, batched=False,
                                    sharded=False, inject=False)
        sfields = state_fields(flags)
        cfields = packed_operand_fields(sfields) if pc else sfields
        bflags = dataclasses.replace(flags, packed_compute=True) if pc \
            else flags
        aux_names = tuple(
            k for k in AUX_FIELDS
            if (k == "edge_iid")
            or (k in ("crash_m", "restart_m") and flags.faults)
            or (k in ("link_fail", "link_heal") and flags.links)
            or (k == "periodic" and flags.periodic)
            or (k == "delay" and flags.delay and cfg.delay_lo < cfg.delay_hi)
        )
        snap_fields = tuple(k for k in tick_states if k in sfields)
        snap_names = tuple(f"{k}@{t}" for t in range(T) for k in snap_fields)

        def kernel(*refs):
            ins = dict(zip(cfields, refs[:len(cfields)]))
            if inkernel:
                n_in = len(cfields) + 3
                kt_loads = [r[...] for r in refs[len(cfields):n_in]]
                slabs, el_tab, b_tab = {}, None, None
            else:
                n_in = len(cfields) + len(aux_names) + 2
                slabs = {k: r[...] for k, r in
                         zip(aux_names, refs[len(cfields):])}
                el_tab = refs[n_in - 2][...].astype(_I32)
                b_tab = refs[n_in - 1][...].astype(_I32)
            outs = dict(zip(cfields + ("overflow",) + snap_names,
                            refs[n_in:]))
            loaded = {k: ins[k][...] for k in cfields}
            parts = {k: [] for k in cfields}
            ov_parts = []
            snap_parts = {k: [[] for _ in range(T)] for k in snap_fields}
            for kk in range(SUB):
                # SUB independent lane slabs = SUB independent T-tick
                # chains (the ILP x fusion composition: the launch overlaps
                # SUB chains, each T lattices deep; no dataflow edges
                # between slabs, so bits are unchanged — the same argument
                # as the 1-tick sub-tiling).
                def slab(v):
                    return v if SUB == 1 else \
                        v[:, kk * sub_w:(kk + 1) * sub_w]
                s = {}
                for k in cfields:
                    v = slab(loaded[k])
                    if k in _BOOL_STATE:
                        s[k] = v != 0
                    elif k in ("log_term", "log_cmd"):
                        s[k] = v
                    else:
                        s[k] = v.astype(_I32)
                if pc:
                    # Unpack ctrl/link ONCE per launch — the
                    # vote-exchange words stay packed across all T
                    # lattices (§18's "packed across fused T ticks").
                    s = _enter_packed_lattice(cfg, s)
                if inkernel:
                    kt = _kt_consts(cfg, scen_keys,
                                    *(slab(v) for v in kt_loads))
                    el_slab = b_slab = None
                else:
                    el_slab, b_slab = slab(el_tab), slab(b_tab)
                ov = {"m": jnp.zeros((N, sub_w), _I32)}

                def sel(table, Wn, delta):
                    # (N, sub_w) values: per node, table rows
                    # [n*Wn, (n+1)*Wn) at per-lane offset delta[n] (one
                    # one-hot contraction per node). An offset past the
                    # window means the structural reset bound was violated:
                    # CLAMP (the kernel stays well-defined) and COUNT into
                    # the overflow output — the caller must discard the
                    # launch (the archival kernel's loud-failure contract).
                    ov["m"] = ov["m"] + (delta >= Wn).astype(_I32)
                    delta = jnp.minimum(delta, Wn - 1)
                    rows_iota = jax.lax.broadcasted_iota(
                        _I32, (Wn, sub_w), 0)
                    vals = []
                    for n in range(N):
                        oh = rows_iota == delta[n][None]
                        vals.append(jnp.sum(
                            jnp.where(oh, table[n * Wn:(n + 1) * Wn], 0),
                            axis=0))
                    return jnp.stack(vals)

                t0, b0 = s["t_ctr"], s["b_ctr"]
                for t in range(T):
                    if inkernel:
                        # §17 in-kernel aux: every channel re-drawn here
                        # from the resident key planes at the LIVE
                        # counters/tick — no tables, no window, no
                        # overflow (ov stays zero), and partitions see
                        # this tick's pre-phase role/up.
                        aux = _kt_aux(cfg, flags, kt, s, t)
                    else:
                        aux = {}
                        for name in aux_names:
                            r = aux_rows[name]
                            v = slab(slabs[name][t * r:(t + 1) * r])
                            aux[name] = (v != 0) if name in _BOOL_AUX \
                                else v.astype(_I32)
                        if flags.faults:
                            aux["el_draw_f"] = sel(el_slab, W,
                                                   s["t_ctr"] - t0)
                        aux["bdraw"] = sel(b_slab, T, s["b_ctr"] - b0)
                    el_dirty = tick_mod.phase_body(cfg, s, aux, bflags)
                    if inkernel:
                        d = rngmod.kt_draw_uniform(
                            kt["tk0"], kt["tk1"], s["t_ctr"] - 1,
                            cfg.el_lo, cfg.el_hi)
                    else:
                        d = sel(el_slab, W, s["t_ctr"] - 1 - t0)
                    s["el_left"] = jnp.where(el_dirty, d, s["el_left"])
                    for k in snap_fields:
                        # Under packed compute the only snapshot field
                        # without a wide in-lattice plane is "votes" —
                        # derive it by the §18 popcount identity.
                        sv = popcount32(s["vote_bits"]) \
                            if pc and k == "votes" else s[k]
                        snap_parts[k][t].append(
                            sv if k in ("log_term", "log_cmd")
                            else sv.astype(_I32))
                if pc:
                    s = _exit_packed_lattice(cfg, s)
                for k in cfields:
                    parts[k].append(
                        s[k] if k in ("log_term", "log_cmd")
                        else s[k].astype(kernel_field_dtype(cfg, k)))
                ov_parts.append(ov["m"])

            def join(ps):
                return ps[0] if SUB == 1 else jnp.concatenate(ps, axis=1)

            for k in cfields:
                outs[k][...] = join(parts[k])
            outs["overflow"][...] = join(ov_parts)
            for t in range(T):
                for k in snap_fields:
                    outs[f"{k}@{t}"][...] = join(snap_parts[k][t])

        def snap_dtype(k):
            return (_I16 if cfg.log_dtype == "int16" else _I32) \
                if k in ("log_term", "log_cmd") else _I32

        in_specs = [block_spec(field_shapes[k]) for k in cfields]
        if inkernel:
            in_specs += [block_spec((4 + len(scen_keys), tile_g)),
                         block_spec((2 * N, tile_g)),
                         block_spec((2 * N, tile_g))]
        else:
            in_specs += [block_spec((T * aux_rows[k], tile_g))
                         for k in aux_names]
            in_specs += [block_spec((N * W, tile_g)),
                         block_spec((N * T, tile_g))]
        out_shapes = [
            jax.ShapeDtypeStruct(
                tuple(field_shapes[k][:-1]) + (lanes,),
                kernel_field_dtype(cfg, k))
            for k in cfields
        ] + [jax.ShapeDtypeStruct((N, lanes), _I32)]  # overflow counts
        out_specs = [block_spec(field_shapes[k]) for k in cfields]
        out_specs += [block_spec((N, tile_g))]
        for _t in range(T):
            for k in snap_fields:
                rows = field_shapes[k][0]
                out_shapes.append(
                    jax.ShapeDtypeStruct((rows, lanes), snap_dtype(k)))
                out_specs.append(block_spec((rows, tile_g)))
        call = pl.pallas_call(
            kernel,
            grid=(lanes // tile_g,),
            in_specs=in_specs,
            out_specs=out_specs,
            out_shape=out_shapes,
            input_output_aliases={i: i for i in range(len(cfields))},
            interpret=interpret,
        )
        return call, cfields, aux_names, snap_fields

    return build_call


def fused_launch_aux(cfg: RaftConfig, base, tkeys, bkeys, tick0, t_ctr,
                     b_ctr, T: int, resets_bound: Optional[int] = None,
                     scen: Optional[dict] = None):
    """The XLA pre-pass of one fused launch: draw the T per-tick aux dicts
    (ops/tick.make_aux over a shim state — every draw is derivable from
    the pre-launch counters and the tick index) plus the counter-keyed
    el/backoff draw tables. Shared by every fused call site
    (make_pallas_scan, make_pallas_tick, parallel/mesh) so the aux
    assembly — the half of the bit-compat contract that lives OUTSIDE the
    kernel — exists exactly once. make_aux also stages the per-tick
    counter-keyed el_draw_f/bdraw draws the fused kernel re-derives from
    the tables; those dict entries are never passed to the kernel
    (aux_names excludes them), so they are dead values XLA's DCE prunes
    at compile — no runtime cost, and the one make_aux stays the single
    source of every other aux bit. Returns (per_tick_aux, flags,
    (el_table, b_table))."""
    import types

    per, flags = [], None
    for k in range(T):
        # Stateless shim: a leader-isolation bank cannot run fused (the
        # per-tick roles are unknown at launch) — resolve_fused_geometry
        # gates that statically; make_aux raises if it slips through.
        shim = types.SimpleNamespace(tick=tick0 + k, t_ctr=t_ctr,
                                     b_ctr=b_ctr)
        aux_k, flags = tick_mod.make_aux(cfg, base, tkeys, bkeys, shim,
                                         None, None, scen=scen)
        per.append(aux_k)
    tabs = draw_tables(cfg, tkeys, bkeys, t_ctr, b_ctr, T,
                       resets_bound=resets_bound)
    return per, flags, tabs


def fused_aux_slabs(per, aux_names):
    """T-stack the per-tick aux dicts into the fused kernel's slab operands
    (bool aux rides as int16 stand-ins, same as cast_aux_in)."""
    return [jnp.concatenate(
        [p[nm].astype(_I16) if nm in _BOOL_AUX else p[nm] for p in per],
        axis=0) for nm in aux_names]


def unpack_fused_outputs(outs, sfields, snap_fields, T: int):
    """Split one fused launch's outputs -> (state dict, overflow (N, G)
    counts, [per-tick snapshot dicts] — tick-major, matching the kernel's
    output order)."""
    ns = len(sfields)
    s2 = dict(zip(sfields, outs[:ns]))
    nf = len(snap_fields)
    ticks = [dict(zip(snap_fields,
                      outs[ns + 1 + t * nf: ns + 1 + (t + 1) * nf]))
             for t in range(T)]
    return s2, outs[ns], ticks


def fused_observe(cfg: RaftConfig, prev_flat, tick_flats, tel, mon,
                  srv=None, srv_kw=None, scen=None):
    """Advance the flight recorder / monitor / §20 serving carry over the
    T per-tick transitions of one fused launch, from the kernel's snapshot
    dicts — the same telemetry_step_arrays / monitor_step_arrays /
    serving_step calls the T=1 flat-carry runner makes between launches,
    so the counters and the latch are bit-equal to the unfused run by
    construction. `prev_flat` is the pre-launch flat state (all fields);
    each entry of `tick_flats` holds the snapshot subset, which covers
    every field the views read. Serving advances BEFORE the monitor each
    tick so the §21 srv_* series columns see the tick's serving pair."""
    from raft_kotlin_tpu.utils import telemetry as telemetry_mod

    N = cfg.n_nodes
    if srv is not None:
        from raft_kotlin_tpu.ops import serving as serving_mod
    for cur in tick_flats:
        if tel is not None:
            tel = telemetry_mod.telemetry_step_arrays(
                telemetry_mod.flat_view(prev_flat, N),
                telemetry_mod.flat_view(cur, N), tel)
        srv_prev = srv
        if srv is not None:
            srv = serving_mod.serving_step(
                cfg, serving_mod.serving_flat_view(cur, N), srv,
                kw=srv_kw, scen=scen)
        if mon is not None:
            mon = telemetry_mod.monitor_step_arrays(
                telemetry_mod.monitor_flat_view(prev_flat, N),
                telemetry_mod.monitor_flat_view(cur, N), mon,
                srv_prev=srv_prev, srv_cur=srv)
        prev_flat = cur
    return tel, mon, srv


def cast_aux_in(aux: dict, aux_names):
    """Order-and-cast the aux kernel operands (the aux half of cast_flat_in;
    the flat-carry runner uses it alone — its state already rides in kernel
    form). Aux blocks are kernel INPUTS only, so they keep their narrow
    (int16) dtypes; bool aux rides as int16 stand-ins."""
    return [aux[k].astype(_I16) if k in _BOOL_AUX else aux[k]
            for k in aux_names]


def cast_flat_in(flat: dict, aux: dict, sfields, aux_names):
    """Order + int32-cast the kernel operands from the flat state/aux dicts."""
    ins = []
    for k in sfields:
        v = flat[k]
        ins.append(v if k in ("log_term", "log_cmd") else v.astype(_I32))
    return ins + cast_aux_in(aux, aux_names)


def cast_flat_out(cfg, outs, sfields, with_dirty: bool = True):
    """Inverse of cast_flat_in for the kernel outputs -> (flat state dict,
    el_dirty): bools from their i32 stand-ins, narrowed ints back to their
    storage dtypes (the kernel computes in i32 — see kernel_field_dtype).
    with_dirty=False: `outs` carries exactly the state fields (the flat-carry
    exit path, where el_left was already materialized) -> (dict, None)."""
    from raft_kotlin_tpu.models.state import field_dtype

    s = {}
    for k, v in zip(sfields, outs[: len(sfields)]):
        want = field_dtype(k, cfg)
        if want == jnp.bool_:
            s[k] = v != 0  # incl. pair bools: unflatten_state re-derives them
        else:
            s[k] = v.astype(want) if v.dtype != want else v
    return s, (outs[-1] != 0) if with_dirty else None


def make_pallas_tick(cfg: RaftConfig, tile_g: Optional[int] = None,
                     interpret: Optional[bool] = None,
                     ilp_subtiles: Optional[int] = None,
                     fused_ticks: int = 1, aux_source: str = "staged",
                     compute: str = "unpacked"):
    """Build tick(state, inject=None, fault_cmd=None[, rng]) -> state — same
    contract and same bits as ops.tick.make_tick(cfg), different compilation
    strategy. `ilp_subtiles` pins the sub-tile ILP count (make_pallas_core);
    None = route_ilp_subtiles' per-shape pick (1 on CPU/interpret).

    `fused_ticks` = T > 1 returns a T-TICK ADVANCER through the fused-T
    kernel instead (ISSUE 7): tick(state[, rng]) -> state after T ticks,
    one kernel launch, bit-identical to T per-tick calls. Driver inputs
    (inject / fault_cmd) are a per-tick API and are rejected — per-tick
    drivers are a T=1 sticky-fallback surface, like trace mode. The
    draw-table overflow flag is checked when the call runs EAGERLY
    (raises RuntimeError); under an outer jit the check cannot run —
    use make_pallas_scan, whose scan-level channels always surface it.

    `aux_source` = "inkernel" (ISSUE 15, §17) draws every aux channel
    inside the kernel from the resident key planes — no make_aux /
    fused_launch_aux pre-pass. inject/fault_cmd are rejected on EVERY
    inkernel path (per-tick driver inputs are a staged surface).

    `compute` = "packed" (ISSUE 16, §18) runs the phase lattice on packed
    words: the wrapper packs the HOT planes entering the launch and
    unpacks them (popcount identities) on exit, so the RaftState surface
    — and the bits — are unchanged."""
    N, C, G = cfg.n_nodes, cfg.phys_capacity, cfg.n_groups
    reject_timeout_windows(cfg)
    if aux_source not in AUX_SOURCES:
        raise ValueError(f"unknown aux_source {aux_source!r}")
    if compute not in COMPUTES:
        raise ValueError(f"unknown compute {compute!r}")
    pc = compute == "packed"
    inkernel = aux_source == "inkernel"
    default_rng: list = []  # derived lazily; wrappers always pass rng explicitly

    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    if fused_ticks > 1:
        tile_g, ilp_subtiles, T_f = resolve_fused_geometry(
            cfg, interpret, tile_g, ilp_subtiles, fused_ticks,
            aux_source=aux_source, compute=compute)
        build_call_f = make_pallas_core(cfg, G, tile_g, interpret,
                                        subtiles=ilp_subtiles,
                                        fused_ticks=T_f,
                                        aux_source=aux_source,
                                        compute=compute)

        def tick_fused(state, inject=None, fault_cmd=None, rng=None):
            assert inject is None and fault_cmd is None, (
                "fused_ticks > 1 takes no per-tick driver inputs "
                "(inject/fault_cmd are a T=1 surface)")
            assert state.term.shape[-1] == G
            if rng is None:
                if not default_rng:
                    with jax.ensure_compile_time_eval():
                        default_rng.append(tick_mod.make_rng(cfg))
                rng = default_rng[0]
            base, tkeys, bkeys, scen = tick_mod.split_rng(rng)
            flat = tick_mod.flatten_state(cfg, state)
            if pc:
                flat = flat_to_packed_compute(cfg, flat)
            if inkernel:
                stat = inkernel_aux_statics(cfg, base, tkeys, bkeys, scen)
                call, sfields, aux_names, _snaps = build_call_f(
                    tick_mod.make_flags(cfg))
                outs = call(*(cast_flat_in(flat, {}, sfields, ())
                              + inkernel_aux_operands(stat, state.tick)))
                s2, ov, _ = unpack_fused_outputs(outs, sfields, (), T_f)
                if pc:
                    s2 = packed_compute_to_flat(cfg, s2)
                    sfields = tuple(s2)
                s, _ = cast_flat_out(cfg, [s2[k] for k in sfields],
                                     sfields, with_dirty=False)
                return RaftState(**tick_mod.unflatten_state(cfg, s),
                                 tick=state.tick + T_f)
            per, flags, (el_tab, b_tab) = fused_launch_aux(
                cfg, base, tkeys, bkeys, state.tick, state.t_ctr,
                state.b_ctr, T_f, scen=scen)
            call, sfields, aux_names, _snaps = build_call_f(flags)
            outs = call(*(cast_flat_in(flat, {}, sfields, ())
                          + fused_aux_slabs(per, aux_names)
                          + [el_tab, b_tab]))
            s2, ov, _ = unpack_fused_outputs(outs, sfields, (), T_f)
            if pc:
                s2 = packed_compute_to_flat(cfg, s2)
                sfields = tuple(s2)
            ov_sum = jnp.sum(ov)
            if not isinstance(ov_sum, jax.core.Tracer) \
                    and int(jax.device_get(ov_sum)):
                raise RuntimeError(
                    "fused-tick kernel draw-table overflow: the launch's "
                    "draws were clamped and its bits are INVALID")
            s, _ = cast_flat_out(cfg, [s2[k] for k in sfields], sfields,
                                 with_dirty=False)
            return RaftState(**tick_mod.unflatten_state(cfg, s),
                             tick=state.tick + T_f)

        return tick_fused
    tile_g, ilp_subtiles = resolve_scan_geometry(
        cfg, interpret, 1, tile_g, ilp_subtiles,
        aux_source=aux_source, compute=compute)

    build_call = make_pallas_core(cfg, G, tile_g, interpret,
                                  subtiles=ilp_subtiles,
                                  aux_source=aux_source,
                                  compute=compute)

    def tick(
        state: RaftState,
        inject: Optional[jax.Array] = None,
        fault_cmd: Optional[jax.Array] = None,
        rng=None,
    ) -> RaftState:
        assert state.term.shape[-1] == G, (
            f"state has {state.term.shape[-1]} groups, kernel built for {G}"
        )
        if rng is None:
            if not default_rng:
                # Eager even under a jit trace (see ops.tick: a staged tracer
                # cached here would leak into later trace signatures).
                with jax.ensure_compile_time_eval():
                    default_rng.append(tick_mod.make_rng(cfg))
            rng = default_rng[0]
        base, tkeys, bkeys, scen = tick_mod.split_rng(rng)
        flat = tick_mod.flatten_state(cfg, state)
        if pc:
            flat = flat_to_packed_compute(cfg, flat)
        if inkernel:
            if inject is not None or fault_cmd is not None:
                raise ValueError(
                    "aux_source='inkernel' takes no per-tick driver inputs "
                    "(inject/fault_cmd are a staged-aux surface)")
            stat = inkernel_aux_statics(cfg, base, tkeys, bkeys, scen)
            call, sfields, aux_names = build_call(tick_mod.make_flags(cfg))
            outs = call(*(cast_flat_in(flat, {}, sfields, ())
                          + inkernel_aux_operands(stat, state.tick)))
        else:
            aux, flags = tick_mod.make_aux(
                cfg, base, tkeys, bkeys, state, inject, fault_cmd,
                scen=scen)
            call, sfields, aux_names = build_call(flags)
            outs = call(*cast_flat_in(flat, aux, sfields, aux_names))
        if pc:
            sdict = packed_compute_to_flat(
                cfg, dict(zip(sfields, outs[:len(sfields)])))
            sfields = tuple(sdict)
            outs = [sdict[k] for k in sfields] + [outs[-1]]
        s, el_dirty = cast_flat_out(cfg, outs, sfields)
        return tick_mod.finish_tick(
            cfg, tkeys, tick_mod.unflatten_state(cfg, s), el_dirty, state.tick)

    return tick


def resets_per_tick_bound(N: int, delay_zero: bool = False) -> int:
    """Structural upper bound on election-timer resets per (node, tick) —
    the t_ctr advance the K-tick kernel's draw table must cover. Recounted
    over ALL reset sites (r4 ADVICE: the original 3N+1 omitted the phase-5
    leader-side response-demote resets and the delay_lo==0 double-delivery):

    - phase F restart: 1
    - phase 2 demotion (round start while not CANDIDATE): 1
    - phase 3 vote-handler adopts: 1 per processed (c, node) exchange, <= N
    - phase 4 demotion (round conclusion while not CANDIDATE): 1
    - phase 5, node as peer: adopt + quirk-d resets, 2 per foreign-leader
      exchange, <= 2(N-1)
    - phase 5, node as leader: response demote (ops/tick.py
      append_exchange's leader leg), 1 per peer exchange, <= N-1

    Sync (and mailbox with delay_lo > 0, where each pair delivers at most
    one in-flight slot per tick): 1+1+N+1+2(N-1)+(N-1) = 4N.
    Mailbox with delay_lo == 0: vote_deliver/append_deliver run TWICE per
    pair per tick (the pre-send in-flight delivery plus the same-iteration
    tau=0 delivery), doubling the phase-3/5 site counts: 8N-3.

    This is a worst case over reset SITES, not a typical-path estimate —
    the draw-table select masks unused entries, so only the bound's
    validity matters; make_pallas_core_k additionally clamps the table
    offset and reports an overflow flag that make_pallas_scan raises on,
    so even a bound violation fails loudly instead of diverging silently."""
    return 8 * N - 3 if delay_zero else 4 * N


def make_pallas_core_k(cfg: RaftConfig, lanes: int, tile_g: int,
                       interpret: bool, K: int,
                       resets_bound: Optional[int] = None):
    """K-ticks-per-launch megakernel builder.

    NEGATIVE RESULT, KEPT AS REFERENCE (round-5 decision, VERDICT r04 weak
    #6): K=2/4/8 measured ~1.5x SLOWER than K=1 on hardware (ROUND4.md item
    1) — no production path uses this. It stays because it is the committed
    evidence ruling out the launch-overhead hypothesis, and round 5 added
    the draw-table overflow guard (r4 ADVICE high) so its bit-compat
    invariant now fails loudly rather than silently. Tests are marked
    @pytest.mark.archival.

    The phase-cut probe (scripts/probe_phase_cuts.py, round 4) shows the
    1-tick kernel is DMA/overhead-bound: a kernel truncated to phases F+0
    costs ~3.3 ms/tick vs ~4.0 full — the state round-trip through HBM plus
    launch overhead dominates, and only phase 5's log one-hots register as
    compute. Running K ticks inside one pallas_call keeps ALL state VMEM-
    resident across the K phase lattices, cutting the dominant state DMA and
    launch overhead by K.

    Randomness stays outside (bit-compat invariant): per-tick aux masks
    arrive as K-stacked row slabs, and the counter-keyed draws (el timeout,
    backoff) arrive as PRE-DRAWN TABLES over the counter windows the launch
    can reach — el: W = resets_per_tick_bound(N) * K entries from t_ctr0,
    backoff: K entries from b_ctr0 (phase 4 consumes at most one backoff
    draw per tick). The kernel selects table entries by one-hot over the
    window, so every draw equals the per-tick path's draw at the same
    counter bit-for-bit; deferred el_left materialization happens in-kernel
    at each tick boundary (same §7 formula as tick.materialize_el).

    Returns build_call(flags) -> (call, sfields, aux_names) where call takes
    [state fields..., aux K-slabs..., el_table (N*W, lanes), b_table
    (N*K, lanes)] and returns the post-K-tick state fields (aliased) plus a
    final (N, lanes) i32 OVERFLOW count: nonzero where a node's counter
    advance exceeded the draw-table window (table offsets are clamped so
    the selected draw is in-window-but-wrong; the caller MUST treat any
    nonzero overflow as invalidating the whole launch — make_pallas_scan
    raises). `resets_bound` overrides the structural per-tick bound
    (tests shrink it to exercise the overflow path)."""
    N, C = cfg.n_nodes, cfg.phys_capacity
    assert lanes % tile_g == 0, (lanes, tile_g)
    log_dt = jnp.int16 if cfg.log_dtype == "int16" else _I32
    if resets_bound is None:
        resets_bound = resets_per_tick_bound(
            N, cfg.uses_mailbox and cfg.delay_lo == 0)
    W = resets_bound * K

    field_shapes = {
        **{k: (N, tile_g) for k in STATE_FIELDS},
        "log_term": (N * C, tile_g), "log_cmd": (N * C, tile_g),
        "responded": (N * N, tile_g), "next_index": (N * N, tile_g),
        "match_index": (N * N, tile_g), "link_up": (N * N, tile_g),
        **{k: (N * N, tile_g) for k in MAILBOX_FIELDS},
        **{k: (N, tile_g) for k in SNAPSHOT_FIELDS},
    }
    aux_rows = {
        "edge_iid": N * N, "crash_m": N, "restart_m": N, "link_fail": N * N,
        "link_heal": N * N, "periodic": 1, "inject": N, "delay": N * N,
    }

    def block_spec(shape):
        return pl.BlockSpec(shape, lambda i: (0, i))

    @functools.lru_cache(maxsize=None)
    def build_call(flags: BodyFlags):
        flags = dataclasses.replace(flags, dyn_log=False, batched=False,
                                    sharded=False, inject=False)
        sfields = state_fields(flags)
        aux_names = tuple(
            k for k in AUX_FIELDS
            if (k == "edge_iid")
            or (k in ("crash_m", "restart_m") and flags.faults)
            or (k in ("link_fail", "link_heal") and flags.links)
            or (k == "periodic" and flags.periodic)
            or (k == "delay" and flags.delay and cfg.delay_lo < cfg.delay_hi)
        )

        def kernel(*refs):
            n_in = len(sfields) + len(aux_names)
            ins = dict(zip(sfields, refs[:len(sfields)]))
            slabs = {k: r[...] for k, r in
                     zip(aux_names, refs[len(sfields):n_in])}
            el_tab = refs[n_in][...].astype(_I32)
            b_tab = refs[n_in + 1][...].astype(_I32)
            outs = dict(zip(sfields + ("overflow",), refs[n_in + 2:]))
            ov = {"m": jnp.zeros((N, tile_g), _I32)}

            def sel(table, Wn, delta):
                # (N, tile) values: per node, table rows [n*Wn, (n+1)*Wn) at
                # per-lane offset delta[n] (one (Wn, tile) one-hot contraction
                # per node — compute is nearly free in this DMA-bound kernel).
                # An offset past the window means the structural reset bound
                # was violated: CLAMP (so a draw is still selected and the
                # kernel stays well-defined) and COUNT into the overflow
                # output — the caller must discard the launch (r4 ADVICE:
                # the old silent 0-draw diverged bit-wise with no error).
                ov["m"] = ov["m"] + (delta >= Wn).astype(_I32)
                delta = jnp.minimum(delta, Wn - 1)
                rows_iota = jax.lax.broadcasted_iota(_I32, (Wn, tile_g), 0)
                vals = []
                for n in range(N):
                    oh = rows_iota == delta[n][None]
                    vals.append(jnp.sum(
                        jnp.where(oh, table[n * Wn:(n + 1) * Wn], 0), axis=0))
                return jnp.stack(vals)
            # Same widen-at-entry boundary as the 1-tick kernel (Mosaic int16
            # layout crash on columnar rows): narrow in HBM, int32 inside.
            s = {}
            for k in sfields:
                v = ins[k][...]
                if k in _BOOL_STATE:
                    s[k] = v != 0
                elif k in ("log_term", "log_cmd"):
                    s[k] = v
                else:
                    s[k] = v.astype(_I32)
            t0, b0 = s["t_ctr"], s["b_ctr"]
            for k in range(K):
                aux = {}
                for name in aux_names:
                    r = aux_rows[name]
                    v = slabs[name][k * r:(k + 1) * r]
                    aux[name] = (v != 0) if name in _BOOL_AUX \
                        else v.astype(_I32)
                if flags.faults:
                    aux["el_draw_f"] = sel(el_tab, W, s["t_ctr"] - t0)
                aux["bdraw"] = sel(b_tab, K, s["b_ctr"] - b0)
                el_dirty = tick_mod.phase_body(cfg, s, aux, flags)
                d = sel(el_tab, W, s["t_ctr"] - 1 - t0)
                s["el_left"] = jnp.where(el_dirty, d, s["el_left"])
            for k in sfields:
                outs[k][...] = (s[k] if k in ("log_term", "log_cmd")
                                else s[k].astype(kernel_field_dtype(cfg, k)))
            outs["overflow"][...] = ov["m"]

        def field_dtype(k):
            return kernel_field_dtype(cfg, k)

        in_specs = [block_spec(field_shapes[k]) for k in sfields]
        in_specs += [block_spec((K * aux_rows[k], tile_g)) for k in aux_names]
        in_specs += [block_spec((N * W, tile_g)), block_spec((N * K, tile_g))]
        out_shapes = [
            jax.ShapeDtypeStruct(
                tuple(field_shapes[k][:-1]) + (lanes,), field_dtype(k))
            for k in sfields
        ] + [jax.ShapeDtypeStruct((N, lanes), _I32)]  # overflow counts
        out_specs = [block_spec(field_shapes[k]) for k in sfields]
        out_specs += [block_spec((N, tile_g))]
        call = pl.pallas_call(
            kernel,
            grid=(lanes // tile_g,),
            in_specs=in_specs,
            out_specs=out_specs,
            out_shape=out_shapes,
            input_output_aliases={i: i for i in range(len(sfields))},
            interpret=interpret,
        )
        return call, sfields, aux_names

    return build_call


def draw_tables(cfg: RaftConfig, tkeys, bkeys, t_ctr, b_ctr, K: int,
                resets_bound: Optional[int] = None):
    """The K-launch counter-keyed draw tables (XLA, outside the kernel):
    el_table (N*W, G) rows n*W + j = draw_uniform_keyed(tkeys, t_ctr0 + j)
    for node n; b_table (N*K, G) likewise over bkeys/b_ctr0. Same counted
    threefry as the per-tick path — table entry == that path's draw at the
    same counter, bit for bit. `resets_bound` must match the kernel's
    (make_pallas_core_k)."""
    from raft_kotlin_tpu.utils import rng as rngmod

    N = cfg.n_nodes
    if resets_bound is None:
        resets_bound = resets_per_tick_bound(
            N, cfg.uses_mailbox and cfg.delay_lo == 0)
    W = resets_bound * K

    def tab(keys, ctr0, Wn, lo, hi):
        draws = jnp.stack([rngmod.draw_uniform_keyed(keys, ctr0 + j, lo, hi)
                           for j in range(Wn)])  # (Wn, N, G)
        # Row n*Wn + j = node n's draw at counter ctr0 + j.
        return draws.transpose(1, 0, 2).reshape(N * Wn, -1)

    return (tab(tkeys, t_ctr, W, cfg.el_lo, cfg.el_hi),
            tab(bkeys, b_ctr, K, cfg.bo_lo, cfg.bo_hi))


def resolve_scan_geometry(cfg: RaftConfig,
                          interpret: Optional[bool] = None,
                          k_per_launch: int = 1,
                          tile_g: Optional[int] = None,
                          ilp_subtiles: Optional[int] = None,
                          aux_source: str = "staged",
                          compute: str = "unpacked"):
    """The (tile_g, ilp_subtiles) a make_pallas_scan call with these same
    arguments resolves to — THE single copy of that resolution, so reporting
    surfaces (bench.py's `ilp_subtiles` field) read the geometry the
    headline kernel actually runs with instead of re-deriving it.
    `aux_source`/`compute` feed the VMEM tile model (default_tile): the
    in-kernel aux path budgets no staged slabs, the packed-compute path
    budgets word planes for the hot fields — both grow G per launch."""
    G = cfg.n_groups
    K = max(1, k_per_launch)
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    if tile_g is None:
        tile_g = default_tile(cfg, G, interpret, k_per_launch=K,
                              aux_source=aux_source, compute=compute)
    if interpret and G % tile_g:
        tile_g = G
    if ilp_subtiles is None:
        ilp_subtiles = route_ilp_subtiles(
            tile_g, "cpu" if interpret else None)
    return tile_g, ilp_subtiles


def resolve_fused_geometry(cfg: RaftConfig,
                           interpret: Optional[bool] = None,
                           tile_g: Optional[int] = None,
                           ilp_subtiles: Optional[int] = None,
                           fused_ticks: Optional[int] = None,
                           snap_rows: int = 0,
                           lanes: Optional[int] = None,
                           platform: Optional[str] = None,
                           aux_source: str = "staged",
                           compute: str = "unpacked"):
    """The (tile_g, ilp_subtiles, fused_ticks) a make_pallas_scan call with
    these arguments resolves to — the fused extension of
    resolve_scan_geometry, and like it THE single copy of the resolution
    (bench.py's `fused_ticks`/`ilp_subtiles` fields read the geometry the
    headline kernel actually runs with; parallel/mesh resolves its
    per-shard geometry through the same call via `lanes`/`platform`).
    fused_ticks=None routes through FUSED_TICK_TABLE (1 on CPU/interpret);
    a ROUTED T that fails the fused VMEM model falls back to T=1 (sticky),
    while an explicitly PINNED T re-raises — a pin is a demand, not a
    hint. `lanes` overrides the lane width (default cfg.n_groups; mesh
    passes the per-device shard width); `platform` overrides the routing
    platform (mesh passes its devices' platform — jax.default_backend()
    can disagree with the mesh under virtual-device test pools)."""
    G = lanes if lanes is not None else cfg.n_groups
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    if platform is None:
        platform = "cpu" if interpret else None
    if cfg.scenario is not None and cfg.scenario.needs_state \
            and aux_source != "inkernel":
        # Leader-isolation partition programs (SEMANTICS.md §12) read the
        # PRE-TICK roles per tick; the STAGED fused kernel precomputes all
        # T aux dicts at launch, before those roles exist. Routed T falls
        # back sticky to 1; a pinned T is a demand and raises. The
        # in-kernel aux path (ISSUE 15, §17) evaluates partitions from the
        # live VMEM role/up planes inside the T-loop, so it is EXEMPT —
        # leader-iso universes fuse only with aux_source="inkernel".
        if fused_ticks is not None and fused_ticks > 1:
            raise ValueError(
                "fused_ticks > 1 cannot run a leader-isolation scenario "
                "bank (cfg.scenario.needs_state) with staged aux: per-tick "
                "aux depends on pre-tick state the fused launch cannot "
                "see; use aux_source='inkernel'")
        fused_ticks = 1
    if fused_ticks is None:
        try:
            base = tile_g if tile_g is not None else \
                default_tile(cfg, G, interpret, aux_source=aux_source,
                             compute=compute)
        except ValueError:
            base = None
        if base is not None and interpret and G % base:
            base = G
        T = route_fused_ticks(base, platform) if base else 1
    else:
        T = max(1, fused_ticks)
    if T > 1:
        try:
            tg = tile_g if tile_g is not None else default_tile(
                cfg, G, interpret, k_per_launch=T, snap_rows=snap_rows,
                aux_source=aux_source, compute=compute)
            if interpret and G % tg:
                tg = G
            k = ilp_subtiles if ilp_subtiles is not None else \
                route_ilp_subtiles(tg, platform)
            return tg, k, T
        except ValueError:
            if fused_ticks is not None:
                raise
            T = 1
    if lanes is None:
        tg, k = resolve_scan_geometry(cfg, interpret, 1, tile_g,
                                      ilp_subtiles,
                                      aux_source=aux_source,
                                      compute=compute)
        return tg, k, 1
    # lanes override (per-shard callers): T=1 geometry at the given width.
    if tile_g is None:
        tile_g = default_tile(cfg, G, interpret, aux_source=aux_source,
                              compute=compute)
    if interpret and G % tile_g:
        tile_g = G
    if ilp_subtiles is None:
        ilp_subtiles = route_ilp_subtiles(tile_g, platform)
    return tile_g, ilp_subtiles, 1


def make_pallas_scan(cfg: RaftConfig, n_ticks: int,
                     tile_g: Optional[int] = None,
                     interpret: Optional[bool] = None,
                     k_per_launch: int = 1,
                     jitted: bool = True,
                     _resets_bound: Optional[int] = None,
                     ilp_subtiles: Optional[int] = None,
                     telemetry: bool = False,
                     monitor: bool = False,
                     fused_ticks: Optional[int] = None,
                     trace: bool = False,
                     layout: str = "wide",
                     aux_source: str = "staged",
                     compute: str = "unpacked",
                     serving: bool = False):
    """Multi-tick Pallas runner with a FLAT int32 scan carry.

    Scanning make_pallas_tick converts RaftState <-> the kernel's flat int32
    layout EVERY tick (bool<->int32 casts, pair/log reshapes); the round-4
    profile attributes ~0.3 ms of the 2.3 ms headline tick to exactly those
    conversion fusions. Here the scan carries the flat kernel form and the
    conversions run once per CALL: flatten+cast before the scan, cast+
    unflatten after. Bits are identical by construction (same phase_body
    kernel, same aux draws, same deferred-draw materialization).

    With k_per_launch = K > 1, full launches run through the K-tick kernel
    (make_pallas_core_k: state crosses HBM once per K ticks) and the
    n_ticks % K remainder through the 1-tick kernel — still bit-identical
    (same phase_body, same counted draws via the launch tables). K > 1
    requires jitted=True: the kernel's draw-table overflow flag is
    host-checked after each call and raises RuntimeError on violation of
    the structural reset bound (clamped draws are WRONG bits — r4 ADVICE).
    `_resets_bound` is a test-only override of that bound.

    `ilp_subtiles` pins the 1-tick kernel's sub-tile ILP count
    (make_pallas_core; None = route_ilp_subtiles per shape, 1 on CPU).
    The archival K-tick kernel stays at K_sub=1.

    `telemetry=True` threads the scan-carry flight recorder
    (utils/telemetry.py) through the flat carry — the accumulation reads
    the pre/post-tick flat state BETWEEN kernel launches (plain XLA
    reductions; the Mosaic kernel and its bits are untouched); `monitor=
    True` threads the scan-carry safety-invariant monitor the same way
    (Figure-3 checks over the flat views — the logs ride the flat carry
    in storage dtype, which the checks compare natively). run returns
    (state[, trace][, telemetry][, monitor-finalized]) accordingly. Both
    require k_per_launch=1: the archival K-tick kernel exposes no per-tick
    state.

    `fused_ticks` = T (ISSUE 7): full T-blocks run through the FUSED-T
    kernel (make_pallas_core(fused_ticks=T): T phase lattices per launch,
    state VMEM-resident between ticks, composed with the sub-tile ILP) and
    the n_ticks % T remainder through the 1-tick kernel — bit-identical by
    the same counted-draw-table argument as the archival K path. None =
    route_fused_ticks per shape (1 on CPU/interpret — the sticky
    fallback); T=1 compiles the byte-identical pre-fusion program.
    Telemetry, monitor and trace WORK under fusion: the fused kernel
    snapshots the observed fields post-tick (fused_snapshot_fields) and
    the accumulation replays the T transitions between launches on the
    flat carry, unchanged (fused_observe) — fusion is carry-transparent.
    The draw-table overflow flag is host-checked per call when jitted=True
    (raises RuntimeError, the archival kernel's loud-failure contract);
    jitted=False embeds in a caller's jit where no host check can run, so
    it requires telemetry=True and surfaces the count as the recorder key
    `fused_draw_overflow` (bench gates on it) — a ROUTED T quietly falls
    back to 1 when that channel is missing, a PINNED T raises.

    `trace=True` additionally returns the per-tick differential trace
    {role, term, commit, last_index}: (n_ticks, N, G) int32 each, identical
    across T by construction (the fused legs read it from the snapshots) —
    the test surface tests/test_fused_ticks.py pins.

    `layout` = "packed" (ISSUE 11) packs the FLAT SCAN CARRY between
    kernel launches into the bit/byte-minimal layout (models/state.
    pack_fields — SEMANTICS.md §14): the body unpacks to the i32 kernel
    form at read and re-packs at write, so the HBM-resident state between
    launches is the packed representation while the Mosaic kernel (and
    its bits) stay untouched. This deliberately reverses the runner's
    entry-cast amortization for the carry — bytes at rest traded for
    elementwise repack ALU; in-kernel unpack is the hardware follow-up.
    The width-overflow latch is host-checked per call when jitted=True
    (RuntimeError, the fused overflow contract); jitted=False requires
    telemetry=True and surfaces the latch as the recorder key
    `packed_width_overflow`. The archival K-tick path rejects packed.

    `aux_source` = "inkernel" (ISSUE 15, §17) routes every launch through
    the in-kernel aux kernels (make_pallas_core(aux_source="inkernel")):
    the per-tick make_aux / fused_launch_aux XLA pre-passes disappear from
    the hot path — the scan body only rebuilds the tiny resident key table
    at the current tick (inkernel_aux_operands) — and the fused overflow
    channel is structurally zero (live-counter draws have no table
    window). Bit-identical to "staged" by the §17 twin pins
    (tests/test_inkernel_aux.py differential suite). Requires
    k_per_launch == 1 (the archival K-tick kernel stays staged-only).

    `compute` = "packed" (ISSUE 16, §18) evaluates the phase lattice on
    packed words INSIDE the kernel (make_pallas_core(compute="packed")):
    the body converts the wide flat carry to the packed operand set at
    each launch and back after it, so the between-launch carry — and
    every observability path reading it (telemetry/monitor/trace,
    §14 pack_fields) — is unchanged. Requires layout="packed" (running
    the lattice packed while storing the carry wide would combine the
    repack ALU of both layouts with the VMEM win of neither; the plan
    layer enforces the same pairing) and k_per_launch == 1. Bit-identical
    to "unpacked" by the §18 popcount identities
    (tests/test_packed_compute.py differential suite).

    Returns run(state, rng) -> state (jitted; rng rides as an operand so the
    compilation is seed-independent, as everywhere else)."""
    import types

    from raft_kotlin_tpu.models import state as state_mod
    from raft_kotlin_tpu.utils import telemetry as telemetry_mod

    reject_timeout_windows(cfg)

    N, G = cfg.n_nodes, cfg.n_groups
    K = max(1, k_per_launch)
    packed = layout == "packed"
    if layout not in ("wide", "packed"):
        raise ValueError(f"unknown layout {layout!r}")
    if aux_source not in AUX_SOURCES:
        raise ValueError(f"unknown aux_source {aux_source!r}")
    if compute not in COMPUTES:
        raise ValueError(f"unknown compute {compute!r}")
    pc = compute == "packed"
    if pc and not packed:
        raise ValueError(
            "compute='packed' requires layout='packed': running the "
            "lattice on packed words while the carry rests wide would "
            "pay both layouts' repack ALU for neither's VMEM win "
            "(autotune.apply_guards pairs them)")
    if pc and K > 1:
        raise ValueError(
            "compute='packed' needs k_per_launch == 1 (the archival "
            "K-tick kernel is an unpacked-compute surface)")
    inkernel = aux_source == "inkernel"
    if inkernel and K > 1:
        raise ValueError(
            "aux_source='inkernel' needs k_per_launch == 1 (the archival "
            "K-tick kernel is a staged-aux surface)")
    if packed and K > 1:
        raise ValueError(
            "layout='packed' needs k_per_launch == 1 (the archival K-tick "
            "kernel exposes no per-tick state to repack between launches)")
    if packed and not jitted and not telemetry:
        raise ValueError(
            "layout='packed' with jitted=False needs telemetry=True: the "
            "runner embeds in the caller's jit, so the width-overflow "
            "latch's only surfaced channel is the flight recorder "
            "(packed_width_overflow)")
    if (telemetry or monitor or trace or serving) and K > 1:
        raise ValueError(
            "telemetry/monitor/trace/serving need k_per_launch == 1: the "
            "K-tick kernel exposes no per-tick state between launches "
            "(archival path; the production fused path is fused_ticks)")
    if serving:
        from raft_kotlin_tpu.ops import serving as serving_mod

        if not serving_mod.serving_enabled(cfg):
            raise ValueError("serving needs cfg.serve_slots > 0")
    if K > 1 and fused_ticks not in (None, 1):
        raise ValueError(
            "k_per_launch (the archival K-tick kernel) and fused_ticks "
            "(the production fused-T engine) are mutually exclusive")
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    if K > 1:
        if cfg.scenario is not None and cfg.scenario.needs_state:
            # Same static gate as resolve_fused_geometry: the archival
            # K-tick kernel precomputes aux from a stateless shim, which
            # leader-isolation banks (§12) cannot feed.
            raise ValueError(
                "k_per_launch > 1 cannot run a leader-isolation scenario "
                "bank (cfg.scenario.needs_state): per-tick aux depends on "
                "pre-tick state the K-tick launch cannot see")
        T_f = 1
        tile_g, ilp_subtiles = resolve_scan_geometry(
            cfg, interpret, K, tile_g, ilp_subtiles)
    else:
        tile_req, ilp_req = tile_g, ilp_subtiles  # caller's pins, if any
        snap_fields = fused_snapshot_fields(
            cfg, telemetry=telemetry, monitor=monitor, trace=trace,
            serving=serving)
        tile_g, ilp_subtiles, T_f = resolve_fused_geometry(
            cfg, interpret, tile_g, ilp_subtiles, fused_ticks,
            snap_rows=_snapshot_rows(cfg, snap_fields),
            aux_source=aux_source, compute=compute)
        if T_f > 1 and not jitted and not telemetry:
            if fused_ticks is not None:
                raise ValueError(
                    "fused_ticks > 1 with jitted=False needs telemetry="
                    "True: the runner embeds in the caller's jit, so the "
                    "draw-table overflow flag's only surfaced channel is "
                    "the flight recorder (fused_draw_overflow)")
            # Routed: sticky fallback, no overflow channel — and the
            # PRE-FUSION geometry is re-resolved from the caller's own
            # pins, so this path compiles the byte-identical unfused
            # program (the fused VMEM model may have shrunk the tile).
            T_f = 1
            tile_g, ilp_subtiles = resolve_scan_geometry(
                cfg, interpret, 1, tile_req, ilp_req,
                aux_source=aux_source, compute=compute)
    build_call = make_pallas_core(cfg, G, tile_g, interpret,
                                  subtiles=ilp_subtiles,
                                  aux_source=aux_source,
                                  compute=compute)
    build_call_k = (make_pallas_core_k(cfg, G, tile_g, interpret, K,
                                       resets_bound=_resets_bound)
                    if K > 1 else None)
    build_call_f = (make_pallas_core(cfg, G, tile_g, interpret,
                                     subtiles=ilp_subtiles,
                                     fused_ticks=T_f,
                                     resets_bound=_resets_bound,
                                     tick_states=snap_fields,
                                     aux_source=aux_source,
                                     compute=compute)
                    if K == 1 and T_f > 1 else None)
    if K > 1 and not jitted:
        raise ValueError(
            "k_per_launch > 1 requires jitted=True: the draw-table overflow "
            "flag must be host-materialized and checked after each call")
    flags_ik = tick_mod.make_flags(cfg)  # the in-kernel builders' flags
    sfields = state_fields(flags_ik)
    if K > 1:
        n_launch, rem = divmod(n_ticks, K)
    elif T_f > 1:
        n_launch, rem = divmod(n_ticks, T_f)
    else:
        n_launch, rem = 0, n_ticks
    C_log = cfg.phys_capacity

    # Packed-carry adapters (ISSUE 11): the flat i32 kernel form <-> the
    # packed rest layout, applied once per scan step around the launch
    # (pair/log reshapes are free; pack_fields/unpack_fields are the one
    # shared encoding — models/state.py).
    def _pack_flat(s):
        canon = {}
        for k in sfields:
            v = s[k]
            if k in tick_mod._PAIR_FIELDS:
                v = v.reshape(N, N, G)
            elif k in tick_mod._LOG_FIELDS:
                v = v.reshape(N, C_log, G)
            canon[k] = v
        return state_mod.pack_fields(cfg, canon)

    def _unpack_flat(p):
        s = state_mod.unpack_fields(cfg, p, kernel_form=True)
        for k in sfields:
            if k in tick_mod._PAIR_FIELDS:
                s[k] = s[k].reshape(N * N, G)
            elif k in tick_mod._LOG_FIELDS:
                s[k] = s[k].reshape(N * C_log, G)
        return s

    def _carry_in(s, ovc, t, tel, mon, srv):
        if not packed:
            return (s, t, tel, mon, srv)
        p, ov2 = _pack_flat(s)
        return (p, ovc | ov2, t, tel, mon, srv)

    def _carry_out(carry):
        if not packed:
            s, t, tel, mon, srv = carry
            return s, jnp.zeros((), bool), t, tel, mon, srv
        p, ovc, t, tel, mon, srv = carry
        return _unpack_flat(p), ovc, t, tel, mon, srv

    def run(state: RaftState, rng):
        base, tkeys, bkeys, scen = tick_mod.split_rng(rng)
        srv_kw = rngmod.kt_key_words(base) if serving else None
        # The inkernel resident operands: computed ONCE per run from the
        # rng operand (bitcasts + stacks — runtime values, so the
        # compilation stays seed-independent like everywhere else).
        stat = (inkernel_aux_statics(cfg, base, tkeys, bkeys, scen)
                if inkernel else None)
        flat = tick_mod.flatten_state(cfg, state)
        # One-time entry casts (the per-tick cost this runner removes): the
        # scan carries the i32 kernel form; storage dtypes return at exit.
        for k in sfields:
            if k not in ("log_term", "log_cmd"):
                flat[k] = flat[k].astype(_I32)

        def body(carry, _):
            s, ovc, t, tel, mon, srv = _carry_out(carry)
            # §18: the carry stays WIDE between launches (telemetry/
            # monitor/§14 pack_fields unchanged) — only the kernel
            # operands cross in the packed-compute form.
            sk = flat_to_packed_compute(cfg, s) if pc else s
            if inkernel:
                # No make_aux pre-pass: the kernel draws its own aux from
                # the resident planes; only the launch-tick row changes.
                call, sfields, aux_names = build_call(flags_ik)
                ins = [sk[k] for k in sfields] \
                    + inkernel_aux_operands(stat, t)
            else:
                # The flat carry holds the real pre-tick rows, so the shim
                # carries role/up too — leader-isolation banks work at T=1.
                shim = types.SimpleNamespace(
                    tick=t, t_ctr=s["t_ctr"], b_ctr=s["b_ctr"],
                    role=s["role"], up=s["up"])
                aux, flags = tick_mod.make_aux(
                    cfg, base, tkeys, bkeys, shim, None, None, scen=scen)
                call, sfields, aux_names = build_call(flags)
                ins = [sk[k] for k in sfields] + cast_aux_in(aux, aux_names)
            with telemetry_mod.engine_scope("pallas"):
                outs = call(*ins)
            s2 = dict(zip(sfields, outs[:-1]))
            if pc:
                s2 = packed_compute_to_flat(cfg, s2)
            s2["el_left"] = tick_mod.materialize_el(
                cfg, tkeys, s2, outs[-1] != 0)
            if tel is not None:
                # Flight recorder on the flat carry (ISSUE 5): plain XLA
                # reductions over the pre/post kernel-form state — the
                # kernel itself, its blocks and its bits are untouched.
                tel = telemetry_mod.telemetry_step_arrays(
                    telemetry_mod.flat_view(s, N),
                    telemetry_mod.flat_view(s2, N), tel)
            srv_prev = srv
            if srv is not None:
                # §20 serving on the flat carry: plain XLA on the post-
                # launch kernel-form state, kernel untouched (same
                # contract as the recorder/monitor). Advanced BEFORE the
                # monitor so the §21 srv_* columns see this tick's pair.
                srv = serving_mod.serving_step(
                    cfg, serving_mod.serving_flat_view(s2, N), srv,
                    kw=srv_kw, scen=scen)
            if mon is not None:
                # Safety-invariant monitor (ISSUE 6): same contract — flat
                # pre/post views between launches, kernel untouched.
                mon = telemetry_mod.monitor_step_arrays(
                    telemetry_mod.monitor_flat_view(s, N),
                    telemetry_mod.monitor_flat_view(s2, N), mon,
                    srv_prev=srv_prev, srv_cur=srv)
            ys = ({f: s2[f] for f in FUSED_TRACE_FIELDS} if trace else None)
            return _carry_in(s2, ovc, t + 1, tel, mon, srv), ys

        def body_k(carry, _):
            s, t, tel, mon, _srv = carry  # tel/mon None (K > 1 rejected)
            per, flags = [], None
            for k in range(K):
                shim = types.SimpleNamespace(
                    tick=t + k, t_ctr=s["t_ctr"], b_ctr=s["b_ctr"])
                aux_k, flags = tick_mod.make_aux(
                    cfg, base, tkeys, bkeys, shim, None, None, scen=scen)
                per.append(aux_k)
            call, sfields_k, aux_names = build_call_k(flags)
            slabs = [jnp.concatenate(
                [p[nm].astype(_I16) if nm in _BOOL_AUX else p[nm]
                 for p in per], axis=0) for nm in aux_names]
            el_tab, b_tab = draw_tables(
                cfg, tkeys, bkeys, s["t_ctr"], s["b_ctr"], K,
                resets_bound=_resets_bound)
            outs = call(*([s[k] for k in sfields_k] + slabs
                          + [el_tab, b_tab]))
            # Last output = the launch's (N, G) draw-table overflow counts.
            return ((dict(zip(sfields_k, outs[:-1])), t + K, tel, mon,
                     _srv), jnp.sum(outs[-1]))

        def body_f(carry, _):
            # One fused-T launch (ISSUE 7): T phase lattices inside one
            # pallas_call, aux T-stacked, counted draws via the launch
            # tables, el_left materialized in-kernel. The recorder/monitor
            # replay the T per-tick transitions from the kernel's snapshot
            # outputs — same step functions as the 1-tick body, so their
            # carries are bit-equal to the unfused run.
            s, ovc, t, tel, mon, srv = _carry_out(carry)
            sk = flat_to_packed_compute(cfg, s) if pc else s
            if inkernel:
                # No fused_launch_aux pre-pass and no draw tables: the
                # T-loop draws every channel in-kernel (ov is structurally
                # zero — live counters have no table window).
                call, sfields_f, aux_names, snaps = build_call_f(flags_ik)
                ins = [sk[k] for k in sfields_f] \
                    + inkernel_aux_operands(stat, t)
            else:
                per, flags, (el_tab, b_tab) = fused_launch_aux(
                    cfg, base, tkeys, bkeys, t, s["t_ctr"], s["b_ctr"],
                    T_f, resets_bound=_resets_bound, scen=scen)
                call, sfields_f, aux_names, snaps = build_call_f(flags)
                ins = [sk[k] for k in sfields_f] \
                    + fused_aux_slabs(per, aux_names) + [el_tab, b_tab]
            with telemetry_mod.engine_scope("pallas-fused"):
                outs = call(*ins)
            s2, ov, ticks_f = unpack_fused_outputs(
                outs, sfields_f, snaps, T_f)
            if pc:
                s2 = packed_compute_to_flat(cfg, s2)
            tel, mon, srv = fused_observe(cfg, s, ticks_f, tel, mon,
                                          srv=srv, srv_kw=srv_kw, scen=scen)
            ys = {"ov": jnp.sum(ov)}
            if trace:
                ys["trace"] = {f: jnp.stack([p[f] for p in ticks_f])
                               for f in FUSED_TRACE_FIELDS}
            return _carry_in(s2, ovc, t + T_f, tel, mon, srv), ys

        tel0 = telemetry_mod.telemetry_zeros() if telemetry else None
        mon0 = telemetry_mod.monitor_init(G, n_ticks, monitor,
                                          **telemetry_mod.ops_kw(cfg))
        srv0 = serving_mod.serving_init(cfg) if serving else None
        flat_t = _carry_in(flat, jnp.zeros((G,), bool), state.tick, tel0,
                           mon0, srv0)
        ov_total = jnp.zeros((), _I32)
        traces = []
        if K > 1 and n_launch:
            flat_t, ovs = jax.lax.scan(body_k, flat_t, None, length=n_launch)
            ov_total = jnp.sum(ovs)
        elif n_launch:
            flat_t, ys = jax.lax.scan(body_f, flat_t, None, length=n_launch)
            ov_total = jnp.sum(ys["ov"])
            if trace:
                traces.append({f: v.reshape((n_launch * T_f,) + v.shape[2:])
                               for f, v in ys["trace"].items()})
        if rem:
            flat_t, ys = jax.lax.scan(body, flat_t, None, length=rem)
            if trace:
                traces.append(ys)
        flat, pov_lanes, t, tel, mon, srv = _carry_out(flat_t)
        # One scalar reduction of the (G,) per-group latch, at scan exit.
        pov = jnp.any(pov_lanes) if packed else pov_lanes
        s, _ = cast_flat_out(cfg, [flat[k] for k in sfields], sfields,
                             with_dirty=False)
        end = RaftState(**tick_mod.unflatten_state(cfg, s), tick=t)
        if K > 1:
            return end, ov_total
        if telemetry and T_f > 1 and not jitted:
            # The jitted=False embedding's overflow channel (see docstring).
            tel = dict(tel)
            tel["fused_draw_overflow"] = ov_total
        if packed and not jitted:
            # Same embedding argument for the packed width latch.
            tel = dict(tel)
            tel["packed_width_overflow"] = pov.astype(_I32)
        out = (end,)
        if trace:
            out = out + ({f: jnp.concatenate([tr[f] for tr in traces])
                          for f in FUSED_TRACE_FIELDS},)
        if telemetry:
            out = out + (tel,)
        if monitor:
            out = out + (telemetry_mod.monitor_finalize(mon),)
        if serving:
            out = out + (srv,)
        if T_f > 1 and jitted:
            out = out + (ov_total,)  # stripped by the checked() wrapper
        if packed and jitted:
            out = out + (pov.astype(_I32),)  # stripped + host-checked
        if (T_f > 1 or packed) and jitted:
            return out
        return out if len(out) > 1 else end

    # jitted=False hands the traceable fn to callers that embed it in a
    # larger jit (bench.measure reduces the end state to scalars INSIDE one
    # jit — a nested pjit would materialize the multi-GB state at the inner
    # call boundary, the exact harness tax the reduction exists to avoid).
    if K > 1:
        inner = jax.jit(run)

        def checked(state, rng):
            end, ov = inner(state, rng)
            if int(jax.device_get(ov)):
                raise RuntimeError(
                    f"K-tick kernel draw-table overflow: a node consumed "
                    f"more election-timer resets within one {K}-tick launch "
                    f"than the structural bound covers "
                    f"(resets_per_tick_bound) — the launch's draws were "
                    f"clamped and its bits are INVALID; results discarded")
            return end

        return checked
    if (T_f > 1 or packed) and jitted:
        inner_f = jax.jit(run)

        def checked_f(state, rng):
            res = inner_f(state, rng)
            if packed:
                res, pov = res[:-1], res[-1]
            if T_f > 1:
                res, ov = res[:-1], res[-1]
                if int(jax.device_get(ov)):
                    raise RuntimeError(
                        f"fused-tick kernel draw-table overflow: a node "
                        f"consumed more election-timer resets within one "
                        f"{T_f}-tick launch than the structural bound "
                        f"covers (resets_per_tick_bound) — the launch's "
                        f"draws were clamped and its bits are INVALID; "
                        f"results discarded")
            if packed:
                state_mod.check_packed_ov(pov)
            return res if len(res) > 1 else res[0]

        return checked_f
    return jax.jit(run) if jitted else run


def default_tile(cfg: RaftConfig, lanes: int, interpret: bool,
                 k_per_launch: int = 1, snap_rows: int = 0,
                 aux_source: str = "staged",
                 compute: str = "unpacked") -> int:
    """VMEM-model tile choice for `lanes` lane columns (raises if none fits).
    k_per_launch > 1 models the K-tick/fused-T kernels: K aux slabs plus
    the el/backoff draw tables replace the single-tick aux set. `snap_rows`
    adds the fused kernel's per-tick snapshot outputs (rows per tick,
    _snapshot_rows): plain stored output blocks, not lattice-live
    temporaries, so they are counted at 1/5 of the model's fitted
    ~20 B/(row,lane) — i.e. at their ~4 B storage cost.

    `aux_source`="inkernel" (the r17-noted model fix): the staged per-tick
    aux slabs, draw tables and the delay plane DON'T exist — only the
    three resident key planes (inkernel_table_rows + 2*2N) and the
    outputs ride in VMEM, so the model grants the larger tile the deleted
    stream paid for. `compute`="packed" (§18): the nine hot planes
    (7 node rows + responded/link_up pair grids) shrink to the four
    packed word planes (3 + 3N rows) in BOTH directions — the ~2x
    VMEM/group cut that feeds back into G per launch."""
    N, C = cfg.n_nodes, cfg.phys_capacity
    K = max(1, k_per_launch)
    if interpret:
        return min(lanes, 256)
    inkernel = aux_source == "inkernel"
    # Rows across all in/out blocks: 2x state (in + aliased out) + worst-case aux
    # + el_dirty.
    n_2d = sum(1 for k in STATE_FIELDS
               if k not in ("log_term", "log_cmd", "responded",
                            "next_index", "match_index", "link_up"))
    log_rows = 2 * 2 * N * C  # 2 log arrays, in + aliased out
    if cfg.log_dtype == "int16":
        log_rows //= 2  # i16 rows cost half the VMEM of the i32 model rows
    if inkernel:
        # §17: no staged slabs, no draw tables, no per-tick aux at all —
        # the resident planes [ktab, tkw, bkw] + el_dirty/overflow out.
        aux_rows = inkernel_table_rows(cfg) + 4 * N + N
        if K > 1:
            aux_rows += -(-K * snap_rows // 5)  # snapshot outputs
    else:
        aux_rows = K * (3 * N * N + 5 * N + 1) + N
        if K > 1:
            # el table N*rb*K + backoff table N*K rows + the overflow output.
            rb = resets_per_tick_bound(
                N, cfg.uses_mailbox and cfg.delay_lo == 0)
            aux_rows += K * N * (rb + 1) + N
            aux_rows += -(-K * snap_rows // 5)  # snapshot outputs (see above)
    if compute == "packed":
        # §18 packed-domain compute: the hot planes cross HBM as words.
        # Unpacked they cost 7N node rows + 2 pair grids (2N^2); packed,
        # 3 ctrl words + 3 N-row word planes — hot_plane_rows() is the
        # shared statement of both sides (bench reports the ratio).
        state_rows = (n_2d - 7) * N + 2 * N * N \
            + hot_plane_rows(cfg, "packed")
    else:
        state_rows = n_2d * N + 4 * N * N
    rows = 2 * state_rows + log_rows + aux_rows
    if cfg.uses_mailbox:
        # §10 mailbox: 13 pair-shaped state fields (in + aliased out) + delay
        # aux (the delay plane only exists on the staged path).
        rows += 2 * len(MAILBOX_FIELDS) * N * N
        if not inkernel:
            rows += N * N
    t = pick_tile(lanes, rows)
    if t is None:
        if pick_tile(lanes) is None:
            raise ValueError(
                f"{lanes} lanes is not a multiple of any supported tile {_TILES}; "
                "pad with pad_groups_for_pallas()")
        raise ValueError(
            f"no tile in {_TILES} dividing {lanes} lanes fits the scoped-VMEM "
            f"budget for n_nodes={N}, phys_capacity={C}; shrink the config or "
            "pass tile_g explicitly")
    return t
