"""Frontier-value cache for the batched deep-log engine — the TEMPORAL lever
(VERDICT r04 missing #1 / next-round item 1).

The batched engine's per-tick read batch (ops/tick.py phase 5) takes ~250
log rows per tick; the round-5 on-chip cost model (ROUND5.md) prices an
XLA:TPU take at ~5 ms per OP + ~0.17 ms per ROW, so those rows are most of
the deep tick. But the protocol only ever reads rows at the per-pair
frontier `next_index(l, p)`, and the frontier moves by at most 1 per
exchange (reference RaftServer.kt:156-167) with two discontinuities: the
quirk-b jump to commit+1 on an election win (RaftServer.kt:112) and the
restart wipe. This module caches the VALUES at the frontier as extra scan
state, maintained incrementally:

- per pair (l, p), 4 values, each with a validity bit:
    f_pli    = l.log_term[ni-2]   (prevLogTerm of the next request)
    f_ent_t  = l.log_term[ni-1]   (the entry's term)
    f_ent_c  = l.log_cmd [ni-1]   (the entry's command)
    f_ppli   = p.log_term[ni-2]   (the peer-side prevLog check row)
- per node, f_topw = log_term[last_index + j] for j in [0, W_TOP) — the
  physical rows an append's §3 GHOST case exposes to the lastLogTerm
  cache: an append at logical index li writes slot phys_len and moves
  last_index to li+1, so the new last_term row is li — f_topw's base row.
  It is a WINDOW (not one value) because a ghost-catching node consumes
  one row per append while the per-tick refill can only top it up once:
  phase-0 appends consume BEFORE the refill runs, so the slack must
  survive a tick of drift.

Maintenance is pure (G,)-wide algebra (ops/tick.py `fcache` hooks):
- frontier +1 (append success): f_pli' = f_ent_t; f_ppli' comes from the
  write the exchange just performed (ghost case propagates invalidity
  lazily); the new entry row is unknown UNTIL the leader's next phase-0
  append writes it — which, at reference pacing, happens before the next
  heartbeat reads it, so steady state needs (almost) NO log reads at all;
- frontier -1 (append failure): shifts run the other way and expose one
  unknown row per stream;
- every deferred log write PATCHES every cache whose (log, row) it hits
  (value + validity), and updates state.last_term live (the §3 rule);
- election win invalidates the winner's streams; restart zeroes them
  (out-of-range rows read as 0 by the engine's convention).

Unknown-but-needed rows are served by ONE small per-tick refill take per
log array with a fixed row budget: per lane, needed-and-invalid cache
entries are ranked (exclusive prefix count over a static enumeration) and
assigned take rows; hard demand beyond the budget — or a consumed-invalid
value — raises the OV flag, and the runner (make_deep_scan) falls back to
re-running the whole call on the plain batched engine. Correctness
therefore never depends on the budget or on any validity reasoning here:
overflow costs time, not bits — and the differential suite pins the two
engines against each other tick-for-tick.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from raft_kotlin_tpu.utils import rng as rngmod
from raft_kotlin_tpu.utils import telemetry as telemetry_mod

_I32 = jnp.int32

# Pair-shaped value fields and the node-shaped top window, canonical order.
PAIR_VALS = ("f_pli", "f_ent_t", "f_ent_c", "f_ppli")
# MAILBOX-ONLY second-entry window (r7): row ni of the owner's log — the
# entry AFTER the frontier. Under known-delivery batching a pair's delivery
# and its next send share one tick, and the with_e shift makes the OLD
# f_ent2 row the entry row the send consumes immediately — without this
# window every same-tick advance+send would consume-invalid and OV the
# whole call, permanently falling back in replication-heavy mailbox
# regimes. Synchronous configs never read the post-shift entry row within
# the shifting tick, so they do not carry (or pay maintenance for) these.
PAIR_VALS_MB = ("f_ent2_t", "f_ent2_c")
NODE_VALS = ("f_topw",)
ALL_VALS = PAIR_VALS + NODE_VALS

# Rows of the above-last_index window (f_topw[(n-1)*W_TOP + j] =
# log_term[last_index + j]).
W_TOP = 4


def ok_name(k: str) -> str:
    return "ok_" + k[2:]


FIELDS = ALL_VALS + tuple(ok_name(k) for k in ALL_VALS)


def pair_vals_for(mailbox: bool) -> tuple:
    """Pair-shaped value fields under a config class: the synchronous set,
    plus the second-entry window for known-delivery mailbox configs."""
    return PAIR_VALS + (PAIR_VALS_MB if mailbox else ())


def fields_for(mailbox: bool) -> tuple:
    """The cache dict's full field set (values + validity) per class —
    the scan-carry layout every fc runner threads through its jit.
    fields_for(False) == FIELDS (the synchronous layout, unchanged)."""
    vals = pair_vals_for(mailbox) + NODE_VALS
    return vals + tuple(ok_name(k) for k in vals)


# Per-tick refill row budgets (term take, cmd take). Sized so that even a
# whole-group election win (3 hard entries x N pairs for the winner) plus
# the soft top-window top-ups fit; exceeding them is not an error, just an
# OV fallback to the plain engine. Mailbox configs carry the extra
# second-entry-window and delivery demands, hence the wider _MB budgets.
TERM_BUDGET = 40
CMD_BUDGET = 12
TERM_BUDGET_MB = 48
CMD_BUDGET_MB = 18


def init_fields(N: int, G: int, mailbox: bool = False) -> dict:
    """All-invalid cache (cold start; runners call refill_all instead)."""
    fc = {}
    for k in pair_vals_for(mailbox):
        fc[k] = jnp.zeros((N * N, G), _I32)
        fc[ok_name(k)] = jnp.zeros((N * N, G), dtype=bool)
    fc["f_topw"] = jnp.zeros((N * W_TOP, G), _I32)
    fc["ok_topw"] = jnp.zeros((N * W_TOP, G), dtype=bool)
    return fc


def refill_all(cfg, state) -> dict:
    """Populate EVERY cache entry from the current state with one flat take
    per log array (the plain engine's full row set, paid once per call
    start instead of every tick)."""
    N, C = cfg.n_nodes, cfg.phys_capacity
    G = state.term.shape[-1]
    ni = state.next_index.reshape(N * N, G).astype(_I32)
    li = state.last_index.astype(_I32)
    lt = state.log_term.reshape(N * C, G)
    lc = state.log_cmd.reshape(N * C, G)

    def pair_rows(delta, owner_side):
        # Global rows for pair (a, b) entries at ni + delta; the owner side
        # reads a's log, the peer side b's log.
        rows = []
        for a in range(1, N + 1):
            for b in range(1, N + 1):
                node = a if owner_side else b
                rows.append((node - 1) * C
                            + jnp.clip(ni[(a - 1) * N + (b - 1)] + delta,
                                       0, C - 1))
        return rows

    mb = cfg.uses_mailbox  # known-delivery fc configs carry f_ent2_*
    top_rows = [li[n - 1] + j for n in range(1, N + 1) for j in range(W_TOP)]
    # (field, take rows, logical rows) segments, in take order.
    segs_t = [("f_pli", pair_rows(-2, True), ni - 2),
              ("f_ent_t", pair_rows(-1, True), ni - 1)]
    segs_c = [("f_ent_c", pair_rows(-1, True), ni - 1)]
    if mb:
        segs_t.append(("f_ent2_t", pair_rows(0, True), ni))
        segs_c.append(("f_ent2_c", pair_rows(0, True), ni))
    segs_t.append(("f_ppli", pair_rows(-2, False), ni - 2))
    segs_t.append(("f_topw",
                   [(n - 1) * C + jnp.clip(top_rows[k], 0, C - 1)
                    for n in range(1, N + 1)
                    for k in range((n - 1) * W_TOP, n * W_TOP)],
                   jnp.stack(top_rows)))
    rows_t = sum((rows for _, rows, _ in segs_t), [])
    rows_c = sum((rows for _, rows, _ in segs_c), [])
    vt = jnp.take_along_axis(lt, jnp.stack(rows_t), axis=0).astype(_I32)
    vc = jnp.take_along_axis(lc, jnp.stack(rows_c), axis=0).astype(_I32)

    def bound(vals, rows):
        # 0 outside [0, C) — the engine's log_gather convention.
        return jnp.where((rows >= 0) & (rows < C), vals, 0)

    fc = {}
    for vals, segs in ((vt, segs_t), (vc, segs_c)):
        at = 0
        for key, rows, logical in segs:
            fc[key] = bound(vals[at:at + len(rows)], logical)
            at += len(rows)
    for k in pair_vals_for(mb):
        fc[ok_name(k)] = jnp.ones((N * N, G), dtype=bool)
    fc["ok_topw"] = jnp.ones((N * W_TOP, G), dtype=bool)
    return fc


def make_deep_scan(cfg, n_ticks: int, return_state: bool = False,
                   telemetry: bool = False, monitor: bool = False,
                   trace: bool = False, layout: str = "wide",
                   serving: bool = False):
    """Multi-tick runner for the frontier-cached deep engine.

    run(state, rng[, summarize]) executes n_ticks through the fcache tick
    in ONE jit (log_cmd live-pinned through the scan carry, scalar
    reductions as outputs — bench.measure's elision discipline), checks the
    OV flag on the host, and on overflow RERUNS the whole call on the plain
    batched engine (bit-identical semantics, no cache) — so callers always
    get plain-engine bits, just faster when the cache held. Returns a dict
    of host-materializable scalars: rounds, livepin, ov (0/1), plus
    whatever `summarize(end_state)` adds. The callable is marked
    `self_timed` for bench.measure (it manages its own jit; measure times
    it through the same host-materialization discipline).

    telemetry=True additionally accumulates the scan-carry flight recorder
    (utils/telemetry.py — incl. per-tick OV events as ov_fallbacks) and
    merges its counters into the reduction dict as tel_* keys;
    monitor=True accumulates the safety-invariant monitor the same way and
    merges its scalars as inv_* keys (reduction mode; with
    return_state=True the call returns (end, ov, monitor-finalized)
    instead of (end, ov)). On an OV fallback the published monitor verdict
    is the PLAIN rerun's — the verdict of the bits actually published.
    Bits are untouched either way (both only read the carried states).

    layout="packed" (ISSUE 11) carries the packed state layout through the
    scan (models/state.pack_state; the frontier cache itself stays wide —
    it is derived working state, not state at rest): external contract
    unchanged, width-overflow latch host-checked per call (RuntimeError —
    re-run with layout="wide")."""
    from raft_kotlin_tpu.models.state import (
        RaftState, check_packed_ov, pack_state, unpack_state)
    from raft_kotlin_tpu.ops import tick as tick_mod

    if cfg.uses_compaction:
        raise ValueError(
            "the frontier-cache engine does not support §15 compaction "
            "(the cache predates the ring map) — plan_for routes "
            "compaction configs to the batched/flat engines")
    tick_plain = tick_mod.make_tick(cfg)
    N, G = cfg.n_nodes, cfg.n_groups
    packed = layout == "packed"
    if layout not in ("wide", "packed"):
        raise ValueError(f"unknown layout {layout!r}")
    if serving:
        from raft_kotlin_tpu.ops import serving as serving_mod

        if not serving_mod.serving_enabled(cfg):
            raise ValueError("serving needs cfg.serve_slots > 0")

    def fc_tick(state, fc, rng):
        base, tkeys, bkeys, scen = tick_mod.split_rng(rng)
        aux, flags = tick_mod.make_aux(cfg, base, tkeys, bkeys, state,
                                       None, None, scen=scen)
        assert flags.batched, "make_deep_scan needs a batched-engine config"
        s = tick_mod.flatten_state(cfg, state)
        fc = dict(fc)
        with telemetry_mod.engine_scope("xla-fcache"):
            el_dirty = tick_mod.phase_body(cfg, s, aux, flags, fcache=fc)
        ov = fc.pop("ov")
        st = tick_mod.finish_tick(cfg, tkeys, tick_mod.unflatten_state(cfg, s),
                                  el_dirty, state.tick)
        return st, fc, ov

    def scan_of(tick_fn, with_fc, with_trace=False):
        def run(st, fc, rng):
            if serving:
                base_k, _tk, _bk, scen_b = tick_mod.split_rng(rng)
                srv_kw = rngmod.kt_key_words(base_k)
            else:
                srv_kw = scen_b = None

            def body(carry, _):
                s, f, acc, ova, tel, mon, srv = carry
                w = unpack_state(cfg, s) if packed else s
                if with_fc:
                    s2, f2, ov = tick_fn(w, f, rng)
                    ov_t = jnp.any(ov)
                    ova = ova | ov_t
                else:
                    s2, f2 = tick_fn(w, rng=rng), f
                    ov_t = None
                if tel is not None:
                    tel = telemetry_mod.telemetry_step(w, s2, tel, ov=ov_t)
                srv_prev = srv
                if srv is not None:
                    # Serving advances BEFORE the monitor folds so the
                    # §21 srv_* series columns see this tick's pair.
                    srv = serving_mod.serving_step(
                        cfg, serving_mod.serving_view(s2), srv, kw=srv_kw,
                        scen=scen_b)
                if mon is not None:
                    mon = telemetry_mod.monitor_step(w, s2, mon,
                                                     srv_prev=srv_prev,
                                                     srv_cur=srv)
                acc = acc + jnp.sum(s2.log_cmd[:, 0, :].astype(_I32))
                y = _trace_row(s2) if with_trace else None
                nxt = pack_state(cfg, s2, ov=s.ov) if packed else s2
                return (nxt, f2, acc, ova, tel, mon, srv), y

            tel0 = telemetry_mod.telemetry_zeros() if telemetry else None
            mon0 = telemetry_mod.monitor_init(cfg.n_groups, n_ticks,
                                              monitor,
                                              **telemetry_mod.ops_kw(cfg))
            srv0 = serving_mod.serving_init(cfg) if serving else None
            st0 = pack_state(cfg, st) if packed else st
            carry0 = (st0, fc, jnp.zeros((), _I32), jnp.zeros((), bool),
                      tel0, mon0, srv0)
            (end, _, acc, ova, tel, mon, srv), ys = jax.lax.scan(
                body, carry0, None, length=n_ticks)
            pov = jnp.any(end.ov != 0) if packed else jnp.zeros((), _I32)
            if packed:
                end = unpack_state(cfg, end)
            return end, acc, ova, tel, mon, srv, ys, pov
        return run

    fc_scan = scan_of(fc_tick, True)
    plain_scan = scan_of(lambda s, rng: tick_plain(s, rng=rng), False)

    if trace:
        # Single-device deep parity leg (ADVICE r5 #3): the "xla-fcache"
        # HEADLINE engine itself produces the differential observable, so
        # deeplog_parity_impl can equal deeplog_impl on the CPU path too.
        # OV contract as everywhere: an overflow discards the fc trace and
        # re-collects it from the plain batched engine with the SAME rng
        # operand — the published trace is always the published bits'.
        fc_scan_t = scan_of(fc_tick, True, with_trace=True)
        plain_scan_t = scan_of(lambda s, rng: tick_plain(s, rng=rng),
                               False, with_trace=True)
        jfc_t = jax.jit(lambda s, r, f: fc_scan_t(s, f, r))
        jplain_t = jax.jit(lambda s, r: plain_scan_t(s, None, r))
        refill_t = jax.jit(lambda s: refill_all(cfg, s))

        def run_trace(st, rng):
            _, _, ova, _tel, _mon, _srv, ys, pov = jfc_t(
                st, rng, refill_t(st))
            ov = bool(jax.device_get(ova))
            if ov:
                _, _, _, _tel, _mon, _srv, ys, pov = jplain_t(st, rng)
            if packed:
                check_packed_ov(pov)
            return jax.device_get(ys), ov

        return run_trace

    def reductions(end, acc, ova, tel, mon, srv, ys, pov, summarize):
        out = _reduction(end, acc, ova.astype(_I32), summarize, tel=tel,
                         mon=mon, srv=srv)
        if packed:
            out["packed_ov"] = pov.astype(_I32)
        return out

    refill_jit = jax.jit(lambda s: refill_all(cfg, s))

    if return_state:
        # Test mode: (full end state, ov: bool) — differential suites
        # compare pytrees and assert on whether the cache actually held.
        jfc_s = jax.jit(lambda s, r, f: fc_scan(s, f, r))
        jplain_s = jax.jit(lambda s, r: plain_scan(s, None, r))

        def run_state(st, rng):
            end, _, ova, _tel, mon, srv, _ys, pov = jfc_s(
                st, rng, refill_jit(st))
            ov = bool(jax.device_get(ova))
            if ov:
                end, _, _, _tel, mon, srv, _ys, pov = jplain_s(st, rng)
            if packed:
                check_packed_ov(pov)
            out = (end, ov)
            if monitor:
                out = out + (telemetry_mod.monitor_finalize(mon),)
            if serving:
                out = out + (srv,)
            return out

        return run_state

    # Keyed by the summarize CALLABLE itself (held strongly — an id() key
    # could be silently reused after GC and return another closure's
    # reductions).
    jitted = {}

    def run(st, rng, summarize=None):
        if summarize not in jitted:
            jitted[summarize] = (
                jax.jit(lambda s, r, f: reductions(
                    *fc_scan(s, f, r), summarize)),
                jax.jit(lambda s, r: reductions(
                    *plain_scan(s, None, r), summarize)),
            )
        jfc, jplain = jitted[summarize]
        fc = refill_jit(st)
        vals = {k: v for k, v in jfc(st, rng, fc).items()}
        if packed:
            check_packed_ov(vals["packed_ov"])
        if int(jax.device_get(vals["ov"])):
            # The plain rerun carries no cache, so its recorder never sees
            # OV events — publish the fc attempt's per-tick OV count (the
            # ticks whose bits the rerun replaced; the counter's semantics)
            # instead of the rerun's structural 0. The monitor's inv_*
            # keys are NOT restored from the fc attempt: the rerun's
            # verdict is the verdict of the published bits.
            fc_ov_ticks = vals.get("tel_ov_fallbacks")
            vals = {k: v for k, v in jplain(st, rng).items()}
            if packed:
                check_packed_ov(vals["packed_ov"])
            vals["ov"] = jnp.ones((), _I32)
            if fc_ov_ticks is not None:
                vals["tel_ov_fallbacks"] = fc_ov_ticks
        return vals

    run.self_timed = True
    return run


def _reduction(end, acc, ov, summarize, tel=None, mon=None, srv=None):
    """THE bench reduction contract (rounds / livepin / ov keys +
    summarize extras + optional tel_* flight-recorder counters + optional
    inv_* monitor scalars) — one copy, shared by every runner here so the
    A/B legs measure() compares can never desynchronize on it."""
    out = {"rounds": jnp.sum(end.rounds), "livepin": acc, "ov": ov}
    if tel is not None:
        out.update({f"tel_{k}": v for k, v in tel.items()})
    if mon is not None:
        out.update(telemetry_mod.monitor_scalars(mon))
    if srv is not None:
        from raft_kotlin_tpu.ops import serving as serving_mod
        out.update(serving_mod.serving_scalars(srv))
    if summarize is not None:
        out.update(summarize(end))
    return out


def _livepin_scan(tick, n_ticks, telemetry: bool = False,
                  monitor: bool = False, n_groups: int = 0,
                  cfg=None, layout: str = "wide"):
    """lax.scan of a per-tick sharded engine under the bench livepin
    discipline (one log_cmd row observed through the carry every tick so
    XLA cannot dead-carry-eliminate the payload chain — bench.measure's
    elision trap), with optional per-tick trace emission, optional
    flight-recorder accumulation, and optional safety-invariant monitor
    accumulation (monitor=True needs n_groups for the taint masks). The
    single copy of the plain-scan body shared by the non-fc sharded
    runners and the fc runner's OV fallback. layout="packed" (needs cfg)
    carries the packed state layout between ticks (unpack-at-read,
    SEMANTICS.md §14) — the trailing `pov` is its width-overflow latch
    (always 0 under "wide");
    scan(st, rng[, with_trace]) -> (end, livepin, tel, mon, trace_ys,
    pov)."""
    from raft_kotlin_tpu.models.state import pack_state, unpack_state

    packed = layout == "packed"
    assert not packed or cfg is not None, "layout='packed' needs cfg"

    def scan(st, rng, with_trace=False):
        def body(carry, _):
            s, acc, tel, mon = carry
            w = unpack_state(cfg, s) if packed else s
            s2 = tick(w, rng)
            acc = acc + jnp.sum(s2.log_cmd[:, 0, :].astype(_I32))
            if tel is not None:
                tel = telemetry_mod.telemetry_step(w, s2, tel)
            if mon is not None:
                mon = telemetry_mod.monitor_step(w, s2, mon)
            y = _trace_row(s2) if with_trace else None
            nxt = pack_state(cfg, s2, ov=s.ov) if packed else s2
            return (nxt, acc, tel, mon), y

        tel0 = telemetry_mod.telemetry_zeros() if telemetry else None
        mon0 = telemetry_mod.monitor_init(n_groups, n_ticks, monitor,
                                          **telemetry_mod.ops_kw(cfg))
        st0 = pack_state(cfg, st) if packed else st
        (end, acc, tel, mon), ys = jax.lax.scan(
            body, (st0, jnp.zeros((), _I32), tel0, mon0), None,
            length=n_ticks)
        pov = jnp.any(end.ov != 0) if packed else jnp.zeros((), _I32)
        if packed:
            end = unpack_state(cfg, end)
        return end, acc, tel, mon, ys, pov

    return scan


def _sharded_default_rng(cfg, mesh):
    """Memoized default rng operand computed straight into its mesh
    placement (init_sharded's pattern — a host-side make_rng + device_put
    would raise on a multi-process mesh). Shared by every sharded runner
    here so the out_shardings contract lives in exactly one place."""
    from raft_kotlin_tpu.ops import tick as tick_mod
    from raft_kotlin_tpu.parallel import mesh as mesh_mod

    memo: list = []

    def default_rng():
        if not memo:
            memo.append(jax.jit(
                lambda: tick_mod.make_rng(cfg),
                out_shardings=mesh_mod.rng_shardings(cfg, mesh))())
        return memo[0]

    return default_rng


def _make_sharded_plain_scan(cfg, mesh, n_ticks: int, engine: str,
                             return_state: bool = False,
                             telemetry: bool = False,
                             monitor: bool = False,
                             layout: str = "wide"):
    """The non-fc sharded deep runners behind make_sharded_deep_scan's
    routing: the per-shard BATCHED or per-pair FLAT shard_map engine
    (parallel.mesh._make_shardmap_xla_tick) scanned for n_ticks under the
    SAME run contract as the fc runner (self_timed reduction dict /
    (state, ov)) — ov is always False here, these engines carry no cache
    to overflow. layout="packed" packs the scan carry (outside shard_map,
    elementwise — the per-shard engine program is untouched and stays
    collective-free; the width latch is host-checked per call)."""
    from raft_kotlin_tpu.models.state import check_packed_ov
    from raft_kotlin_tpu.parallel import mesh as mesh_mod

    packed = layout == "packed"
    tick = mesh_mod._make_shardmap_xla_tick(
        cfg, mesh, batched=(engine == "batched"))
    scan = _livepin_scan(lambda s, rng: tick(s, rng), n_ticks,
                         telemetry=telemetry, monitor=monitor,
                         n_groups=cfg.n_groups, cfg=cfg, layout=layout)
    default_rng = _sharded_default_rng(cfg, mesh)

    if return_state:
        jscan = jax.jit(scan)

        def run_state(st, rng=None):
            rng = rng if rng is not None else default_rng()
            end, _, _tel, _mon, _ys, pov = jscan(st, rng)
            if packed:
                check_packed_ov(pov)
            return end, False

        return run_state

    jitted = {}

    def run(st, rng=None, summarize=None):
        rng = rng if rng is not None else default_rng()
        if summarize not in jitted:
            def reduced(s, r):
                end, acc, tel, mon, _ys, pov = scan(s, r)
                out = _reduction(end, acc, jnp.zeros((), _I32), summarize,
                                 tel=tel, mon=mon)
                if packed:
                    out["packed_ov"] = pov.astype(_I32)
                return out

            jitted[summarize] = jax.jit(reduced)
        vals = dict(jitted[summarize](st, rng).items())
        if packed:
            check_packed_ov(vals["packed_ov"])
        return vals

    run.self_timed = True
    return run


def _trace_row(st):
    """The per-tick differential observable (native.oracle.TRACE_FIELDS) —
    shared by the trace-mode scans the deep parity leg consumes."""
    return {"role": st.role, "term": st.term, "commit": st.commit,
            "last_index": st.last_index, "voted_for": st.voted_for,
            "rounds": st.rounds, "up": st.up}


def make_sharded_deep_scan(cfg, mesh, n_ticks: int,
                           return_state: bool = False,
                           engine: str = "auto",
                           trace: bool = False,
                           telemetry: bool = False,
                           monitor: bool = False,
                           layout: Optional[str] = None):
    """The sharded deep-log runner — and, since round 6, the deep band's
    engine ROUTER: `engine="auto"` (the default every production caller
    uses) picks the per-shard engine ("fc" | "batched" | "flat") from
    parallel.mesh.route_deep_engine's measured crossover table by the
    (log capacity, per-shard lane width, mailbox) SHAPE — no platform-class
    pick remains. "fc"/"batched"/"flat" pin an engine explicitly (bench A/B
    legs, differential tests). All three are bit-identical (the routing
    differential suite pins them pairwise across the crossover).

    §10 mailbox configs route through the same table for delay_lo >= 1
    (the known-delivery regime, r7 — ops/tick.py batches the delivery read
    set up front); τ=0 mailbox configs pin "flat" (per-pair) — the only
    engine whose reads may depend on same-tick slot state.

    `trace=True` (fc engine only — the deep parity leg's observable):
    run(state[, rng]) -> (per-tick trace dict of (T, N, G) arrays over
    native.oracle.TRACE_FIELDS, ov) — on cache overflow the trace is
    re-collected from the plain sharded engine, so the published bits are
    plain-engine bits either way (the usual OV contract).

    The fc engine a multi-chip config-5 run executes per shard:

    Division of labor follows parallel/mesh._make_shardmap_xla_tick: the
    RNG/aux draws stay globally-sharded XLA OUTSIDE shard_map (counted
    threefry under jax_threefry_partitionable — per-shard local draws
    would produce different bits), while the phase lattice WITH the
    frontier cache runs per shard (the cache arrays are groups-minor and
    shard on their lane axis like every state array; the refill takes and
    their lax.cond run shard-locally, so a quiet shard skips its takes
    even while another is refilling). The initial cache fill also runs
    inside shard_map — take_along_axis must never meet the SPMD
    partitioner (the CPU blowup parallel/mesh.py documents).

    OV handling matches make_deep_scan: one host check after the scan; on
    overflow the call re-runs on the plain sharded batched engine
    (parallel.mesh.make_sharded_run) — bits never depend on the cache.

    `telemetry=True` (reduction mode only) accumulates the scan-carry
    flight recorder (utils/telemetry.py; per-tick OV events count into
    ov_fallbacks) and merges tel_* counters into the reduction dict;
    `monitor=True` (reduction mode only) accumulates the safety-invariant
    monitor and merges its inv_* scalars — on an OV fallback the rerun's
    verdict is published (the verdict of the published bits). Both read
    the globally-sharded states OUTSIDE shard_map, so their reductions
    are the same class of cross-shard collectives as the livepin — and
    the per-shard engine program is untouched (group indices in the latch
    are GLOBAL for the same reason).

    run(state, rng=None[, summarize]) -> dict of host scalars (self_timed,
    bench.measure contract); with return_state=True -> (state, ov).

    `layout`="packed" (ISSUE 11) carries the packed state layout through
    every scan here — packing runs OUTSIDE shard_map on the globally
    sharded state (elementwise INCLUDING the (G,) per-group width latch,
    so the per-tick program stays shard-local and collective-free; the
    latch's scalar reduction happens once at scan exit, the observers'
    collective class), and the per-shard engine program is untouched.
    The default None adopts the plan's layout under engine="auto" and
    means "wide" otherwise; an EXPLICIT "wide" always wins over the
    routed plan (the documented overflow remedy)."""
    import math

    from jax.sharding import NamedSharding, PartitionSpec as P

    from raft_kotlin_tpu.models.state import (
        check_packed_ov, pack_state, unpack_state)
    from raft_kotlin_tpu.ops import tick as tick_mod
    from raft_kotlin_tpu.parallel import mesh as mesh_mod

    G = cfg.n_groups
    n_dev = math.prod(mesh.devices.shape)
    assert G % n_dev == 0, "pad_groups first"
    if engine == "auto":
        # The unified plan layer (parallel/autotune.plan_for, r13): one
        # resolution composes the τ=0-mailbox flat guard, the per-shard
        # lane width, and the measured crossover table — this runner no
        # longer consults a table of its own.
        from raft_kotlin_tpu.parallel.autotune import plan_for

        plan = plan_for(cfg, mesh)
        engine = plan["engine"]
        if layout is None:
            layout = plan.get("layout", "wide")
    layout = layout or "wide"
    packed = layout == "packed"
    if layout not in ("wide", "packed"):
        raise ValueError(f"unknown layout {layout!r}")
    assert engine in ("fc", "batched", "flat"), engine
    assert not (cfg.uses_compaction and engine == "fc"), (
        "the frontier-cache engine does not support §15 compaction — "
        "plan_for routes compaction configs to batched/flat")
    assert not (cfg.uses_mailbox and not cfg.known_delivery
                and engine != "flat"), \
        "τ=0 mailbox configs support only the per-pair flat engine"
    if engine != "fc":
        assert not trace, "trace mode is the fc parity leg's observable"
        return _make_sharded_plain_scan(cfg, mesh, n_ticks, engine,
                                        return_state, telemetry=telemetry,
                                        monitor=monitor, layout=layout)
    flags = tick_mod.make_flags(cfg)
    assert flags.batched, "make_sharded_deep_scan needs a batched config"
    sfields = tick_mod.state_fields(flags)
    lanes = P(None, ("dcn", "ici"))
    FC = fields_for(cfg.uses_mailbox)

    def refill_shard(state):
        # Per-shard full cache fill (refill_all's math on local arrays;
        # refill_all only reads .term for the lane width plus the four
        # arrays below, so a light stand-in object suffices).
        def body(ni, li, lt, lc):
            fake = type("S", (), {})()
            fake.term = ni[0]
            fake.next_index = ni
            fake.last_index = li
            fake.log_term = lt
            fake.log_cmd = lc
            fc = refill_all(cfg, fake)
            return tuple(fc[k] for k in FC)

        outs = mesh_mod.shard_map_compat(
            body, mesh=mesh,
            in_specs=(P(None, None, ("dcn", "ici")),
                      lanes,
                      P(None, None, ("dcn", "ici")),
                      P(None, None, ("dcn", "ici"))),
            out_specs=(lanes,) * len(FC),
            check_vma=False,
        )(state.next_index, state.last_index, state.log_term, state.log_cmd)
        return dict(zip(FC, outs))

    def tick_fc(state, fc, rng):
        base, tkeys, bkeys, scen = tick_mod.split_rng(rng)
        aux, flags2 = tick_mod.make_aux(cfg, base, tkeys, bkeys, state,
                                        None, None, scen=scen)
        aux_names = tuple(k for k in tick_mod.AUX_FIELDS if k in aux)
        flat = tick_mod.flatten_state(cfg, state)
        n_s, n_a = len(sfields), len(aux_names)

        def body(*arrs):
            s = dict(zip(sfields, arrs[:n_s]))
            a = dict(zip(aux_names, arrs[n_s:n_s + n_a]))
            fcd = dict(zip(FC, arrs[n_s + n_a:]))
            el_dirty = tick_mod.phase_body(cfg, s, a, flags2, fcache=fcd)
            ov = fcd.pop("ov")
            return (tuple(s[k] for k in sfields)
                    + tuple(fcd[k] for k in FC)
                    + (el_dirty, ov[None, :]))

        ins = ([flat[k] for k in sfields] + [aux[k] for k in aux_names]
               + [fc[k] for k in FC])
        with telemetry_mod.engine_scope("shardmap-fcache"):
            outs = mesh_mod.shard_map_compat(
                body, mesh=mesh,
                in_specs=(lanes,) * len(ins),
                out_specs=(lanes,) * (n_s + len(FC) + 2),
                check_vma=False,
            )(*ins)
        s2 = dict(zip(sfields, outs[:n_s]))
        fc2 = dict(zip(FC, outs[n_s:n_s + len(FC)]))
        st2 = tick_mod.finish_tick(
            cfg, tkeys, tick_mod.unflatten_state(cfg, s2),
            outs[-2], state.tick)
        return st2, fc2, outs[-1][0]

    def scan_fc(st, rng, with_trace=False):
        fc0 = refill_shard(st)

        def body(carry, _):
            s, f, acc, ova, tel, mon = carry
            w = unpack_state(cfg, s) if packed else s
            s2, f2, ov = tick_fc(w, f, rng)
            acc = acc + jnp.sum(s2.log_cmd[:, 0, :].astype(_I32))
            ov_t = jnp.any(ov)
            if tel is not None:
                tel = telemetry_mod.telemetry_step(w, s2, tel, ov=ov_t)
            if mon is not None:
                mon = telemetry_mod.monitor_step(w, s2, mon)
            y = _trace_row(s2) if with_trace else None
            nxt = pack_state(cfg, s2, ov=s.ov) if packed else s2
            return (nxt, f2, acc, ova | ov_t, tel, mon), y

        tel0 = telemetry_mod.telemetry_zeros() if telemetry else None
        mon0 = telemetry_mod.monitor_init(cfg.n_groups, n_ticks, monitor,
                                          **telemetry_mod.ops_kw(cfg))
        st0 = pack_state(cfg, st) if packed else st
        carry0 = (st0, fc0, jnp.zeros((), _I32), jnp.zeros((), bool),
                  tel0, mon0)
        (end, _, acc, ova, tel, mon), ys = jax.lax.scan(
            body, carry0, None, length=n_ticks)
        pov = jnp.any(end.ov != 0) if packed else jnp.zeros((), _I32)
        if packed:
            end = unpack_state(cfg, end)
        return end, acc, ova, tel, mon, ys, pov

    # Plain sharded fallback: the per-tick shard_map BATCHED engine
    # (parallel/mesh's deep route), scanned with the SAME rng operand the
    # fc scan ran with — the OV rerun must reproduce the rep's bits, not
    # the cfg-seed's (and is built ONCE, so an overflow rep pays execution,
    # not a retrace).
    plain_tick = mesh_mod._make_shardmap_xla_tick(cfg, mesh)
    scan_plain = _livepin_scan(lambda s, rng: plain_tick(s, rng), n_ticks,
                               telemetry=telemetry, monitor=monitor,
                               n_groups=cfg.n_groups, cfg=cfg,
                               layout=layout)

    default_rng = _sharded_default_rng(cfg, mesh)

    if trace:
        # Deep parity leg (VERDICT r5 next-round #6): the HEADLINE engine
        # itself produces the differential observable. OV contract as
        # everywhere: an overflow discards the fc trace and re-collects it
        # from the plain sharded engine with the SAME rng operand.
        jfc_t = jax.jit(lambda s, r: scan_fc(s, r, True))
        jplain_t = jax.jit(lambda s, r: scan_plain(s, r, True))

        def run_trace(st, rng=None):
            rng = rng if rng is not None else default_rng()
            _, _, ova, _tel, _mon, ys, pov = jfc_t(st, rng)
            ov = bool(jax.device_get(ova))
            if ov:
                _, _, _tel, _mon, ys, pov = jplain_t(st, rng)
            if packed:
                check_packed_ov(pov)
            return jax.device_get(ys), ov

        return run_trace

    if return_state:
        jfc_s = jax.jit(scan_fc)
        jplain_s = jax.jit(scan_plain)

        def run_state(st, rng=None):
            rng = rng if rng is not None else default_rng()
            end, _, ova, _tel, _mon, _ys, pov = jfc_s(st, rng)
            ov = bool(jax.device_get(ova))
            if ov:
                end, _, _tel, _mon, _ys, pov = jplain_s(st, rng)
            if packed:
                check_packed_ov(pov)
            return end, ov

        return run_state

    # Keyed by the summarize CALLABLE itself (held strongly — an id() key
    # could be silently reused after GC and return another closure's
    # reductions).
    jitted = {}

    def run(st, rng=None, summarize=None):
        rng = rng if rng is not None else default_rng()
        if summarize not in jitted:
            def reduced(s, r):
                end, acc, ova, tel, mon, _ys, pov = scan_fc(s, r)
                out = _reduction(end, acc, ova.astype(_I32), summarize,
                                 tel=tel, mon=mon)
                if packed:
                    out["packed_ov"] = pov.astype(_I32)
                return out

            def reduced_plain(s, r):
                end, acc, tel, mon, _ys, pov = scan_plain(s, r)
                out = _reduction(end, acc, jnp.ones((), _I32), summarize,
                                 tel=tel, mon=mon)
                if packed:
                    out["packed_ov"] = pov.astype(_I32)
                return out

            jitted[summarize] = (jax.jit(reduced), jax.jit(reduced_plain))
        jfc, jplain = jitted[summarize]
        vals = dict(jfc(st, rng).items())
        if packed:
            check_packed_ov(vals["packed_ov"])
        if int(jax.device_get(vals["ov"])):
            # As in make_deep_scan: the plain rerun's recorder sees no OV
            # events, so keep the fc attempt's per-tick fallback count.
            fc_ov_ticks = vals.get("tel_ov_fallbacks")
            vals = dict(jplain(st, rng).items())
            if packed:
                check_packed_ov(vals["packed_ov"])
            if fc_ov_ticks is not None:
                vals["tel_ov_fallbacks"] = fc_ov_ticks
        return vals

    run.self_timed = True
    return run
