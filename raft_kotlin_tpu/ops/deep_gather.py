"""Pallas batched log-row gather — the deep-log read engine.

Round-4 on-chip cost model (scripts/probe_deep_costs.py, BENCH attribution):
an XLA:TPU `take_along_axis` on a (C, G) operand costs ~0.5 ms per OP plus
~0.17 ms per index ROW at G=13k, essentially INDEPENDENT of C — the lowering
is per-lane serial, so the batched deep engine's ~35 takes were ~90% of the
155 ms config-5 tick. This kernel replaces all of them with ONE pallas_call:

- grid (node, C-chunk, G-tile); each step DMAs a (Cb, tile) slab of that
  node's log_term/log_cmd (the whole log crosses HBM exactly once per tick,
  ~4.5 ms at config-5 scale vs ~90 ms of gathers);
- row extraction happens in VMEM via full-shape `jnp.take_along_axis`
  (Mosaic's tpu.dynamic_gather: indices must have the operand's shape, so
  the (R, tile) row matrix is padded with zeros to (Cb, tile) and the first
  R rows of the result are kept);
- out-of-chunk rows are merged across chunk steps by revisiting the output
  block (accumulation pattern: the (R, tile) output block's index_map
  ignores the chunk axis).

Contract: rows are PHYSICAL slot indices already clipped to [0, C);
returned values are the raw storage dtype (callers widen and apply their
own out-of-range masking, exactly as they did after an XLA take).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_I32 = jnp.int32
_G_TILES = (512, 256, 128)

# Escape hatch: force the XLA take_along_axis fallback (differential tests
# pin kernel-vs-takes equality through this; also a field kill switch).
DISABLE = bool(os.environ.get("RAFT_DISABLE_GATHER_KERNEL"))


def _chunk(C: int) -> int:
    """Largest divisor of C that keeps a (Cb, tile) slab comfortably in VMEM
    (~2 MB at int16/tile 512). Non-power-of-two capacities (e.g. the
    config-5 C=10_000) get their largest divisor <= 2500."""
    for d in range(min(C, 2500), 0, -1):
        if C % d == 0:
            return d
    return C


def _tile(G: int, interpret: bool):
    if interpret:
        return G
    for t in _G_TILES:
        if G % t == 0:
            return t
    return None


@functools.lru_cache(maxsize=None)
def build_gather(N: int, C: int, Rt: int, Rc: int, ldt_name: str, G: int,
                 interpret: bool):
    """-> callable(log_term (N*C, G) ldt, log_cmd (N*C, G) ldt,
                   rows_t (N*Rt, G) i32, rows_c (N*Rc, G) i32)
       -> (vals_t (N*Rt, G) ldt, vals_c (N*Rc, G) ldt)
    with vals_x[n*R + r, g] = log_x[n*C + rows_x[n*R + r, g], g].
    Returns None when no supported G-tile divides G (caller falls back to
    XLA takes)."""
    ldt = jnp.dtype(ldt_name)
    tile = _tile(G, interpret)
    if tile is None:
        return None
    Cb = _chunk(C)
    n_chunks = C // Cb
    assert Cb > max(Rt, Rc), (Cb, Rt, Rc)

    def kernel(lt_ref, lc_ref, rt_ref, rc_ref, ot_ref, oc_ref):
        # The chunk axis is the INNERMOST grid dim: output blocks are only
        # accumulated across CONSECUTIVE grid steps mapping to the same
        # block, so all chunks of one (node, g-tile) must run back to back.
        c = pl.program_id(2)

        @pl.when(c == 0)
        def _init():
            ot_ref[...] = jnp.zeros_like(ot_ref)
            oc_ref[...] = jnp.zeros_like(oc_ref)

        j0 = c * Cb
        for blk_ref, rows_ref, out_ref, R in (
            (lt_ref, rt_ref, ot_ref, Rt),
            (lc_ref, rc_ref, oc_ref, Rc),
        ):
            rows = rows_ref[...]
            rel = rows - j0
            hit = (rel >= 0) & (rel < Cb)
            relc = jnp.clip(rel, 0, Cb - 1)
            idx_full = jnp.concatenate(
                [relc, jnp.zeros((Cb - R, tile), _I32)], axis=0)
            # Widen to i32 for the dynamic_gather, narrow back after: Mosaic's
            # gather support is solid on 32-bit lanes; the cast is VMEM-local.
            vals = jnp.take_along_axis(
                blk_ref[...].astype(_I32), idx_full, axis=0)[:R]
            out_ref[...] = jnp.where(hit, vals.astype(out_ref.dtype),
                                     out_ref[...])

    grid = (N, G // tile, n_chunks)
    call = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((Cb, tile), lambda n, i, c: (n * n_chunks + c, i)),
            pl.BlockSpec((Cb, tile), lambda n, i, c: (n * n_chunks + c, i)),
            pl.BlockSpec((Rt, tile), lambda n, i, c: (n, i)),
            pl.BlockSpec((Rc, tile), lambda n, i, c: (n, i)),
        ],
        out_specs=[
            pl.BlockSpec((Rt, tile), lambda n, i, c: (n, i)),
            pl.BlockSpec((Rc, tile), lambda n, i, c: (n, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N * Rt, G), ldt),
            jax.ShapeDtypeStruct((N * Rc, G), ldt),
        ],
        interpret=interpret,
    )
    return call
