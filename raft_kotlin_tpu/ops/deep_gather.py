"""Pallas batched log-row gather — interpret-mode reference of the deep-log
read batch (NOT the TPU path; see build_gather for the Mosaic limitation).

NEGATIVE RESULT, KEPT AS REFERENCE (round-5 decision, VERDICT r04 weak #6):
Mosaic's 8-row dynamic_gather limit makes this kernel uncompilable on real
TPU; it stays as the interpret-mode differential reference for the read
batch's semantics and as the committed evidence for the spatial-gather
ruling-out. Tests are marked @pytest.mark.archival.

Round-4 on-chip cost model (scripts/probe_deep_costs.py, BENCH attribution):
an XLA:TPU `take_along_axis` on a (C, G) operand costs ~0.5 ms per OP plus
~0.16 ms per index ROW at G=13k, essentially INDEPENDENT of C and of layout
(axis-0, lane-major axis-1, and flat-linear forms all cost the same —
scripts/probe_gather_forms.py) — the lowering is per-lane serial. This
kernel was designed to replace all of them with ONE pallas_call:

- grid (node, C-chunk, G-tile); each step DMAs a (Cb, tile) slab of that
  node's log_term/log_cmd (the whole log crosses HBM exactly once per tick,
  ~4.5 ms at config-5 scale vs ~90 ms of gathers);
- row extraction happens in VMEM via full-shape `jnp.take_along_axis`
  (Mosaic's tpu.dynamic_gather: indices must have the operand's shape, so
  the (R, tile) row matrix is padded with zeros to (Cb, tile) and the first
  R rows of the result are kept);
- out-of-chunk rows are merged across chunk steps by revisiting the output
  block (accumulation pattern: the (R, tile) output block's index_map
  ignores the chunk axis).

Contract: rows are PHYSICAL slot indices already clipped to [0, C);
returned values are the raw storage dtype (callers widen and apply their
own out-of-range masking, exactly as they did after an XLA take).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_I32 = jnp.int32
_G_TILES = (512, 256, 128)

# Escape hatch: force the XLA take_along_axis fallback (differential tests
# pin kernel-vs-takes equality through this; also a field kill switch).
DISABLE = bool(os.environ.get("RAFT_DISABLE_GATHER_KERNEL"))


def _chunk(C: int):
    """Largest divisor of C that keeps a (Cb, tile) slab comfortably in VMEM
    (~2 MB at int16/tile 512). Mosaic requires the sublane block dim be a
    multiple of 8 (the block never equals the full (N*C) first dim), so only
    multiples of 8 qualify; None = no valid chunking (caller falls back to
    XLA takes). The config-5 C=10_000 gets 2000."""
    for d in range(min(C, 2500), 7, -1):
        if C % d == 0 and d % 8 == 0:
            return d
    return None


def _tile(G: int, interpret: bool):
    if interpret:
        return G
    for t in _G_TILES:
        if G % t == 0:
            return t
    return None


@functools.lru_cache(maxsize=None)
def build_gather(N: int, C: int, Rt: int, Rc: int, ldt_name: str, G: int,
                 interpret: bool):
    """-> callable(log_term (N*C, G) ldt, log_cmd (N*C, G) ldt,
                   rows_t (N*Rt, G) i32, rows_c (N*Rc, G) i32)
       -> (vals_t (N*Rt, G) ldt, vals_c (N*Rc, G) ldt)
    with vals_x[n*R + r, g] = log_x[n*C + rows_x[n*R + r, g], g].
    Returns None when no supported G-tile divides G (caller falls back to
    XLA takes)."""
    ldt = jnp.dtype(ldt_name)
    if not interpret:
        # Round-4 TPU probe result: Mosaic's tpu.dynamic_gather only supports
        # sublane gathers WITHIN one vreg (8 rows) — take_along_axis on a
        # (Cb, tile) block with Cb in {16..2048} is an internal compiler error
        # on real hardware (scripts/probe_gather_forms.py sweep; the 8-row
        # case is the only one that compiles). A hierarchical 8-row
        # decomposition degenerates to a full one-hot stream over C, which is
        # VPU-compute-bound ~20x above the DMA cost it was meant to save. The
        # kernel therefore runs only in interpreter mode (differential tests
        # pin its semantics); on TPU the engine uses the XLA takes whose
        # measured cost model lives in the module docstring.
        return None
    tile = _tile(G, interpret)
    if tile is None:
        return None
    Cb = _chunk(C)
    if Cb is None:
        return None
    n_chunks = C // Cb
    # Row-block heights must also be sublane-aligned (multiple of 8): pad the
    # row matrices with zero rows (a clipped slot-0 gather, sliced off below).
    Rtp, Rcp = -(-Rt // 8) * 8, -(-Rc // 8) * 8
    if Cb <= max(Rtp, Rcp):
        # Pathological capacity (e.g. C=2504 -> largest 8-multiple divisor
        # 8): the in-chunk concat below needs Cb >= padded row count. Same
        # graceful fallback as every other unsupported shape.
        return None

    def kernel(lt_ref, lc_ref, rt_ref, rc_ref, ot_ref, oc_ref):
        # The chunk axis is the INNERMOST grid dim: output blocks are only
        # accumulated across CONSECUTIVE grid steps mapping to the same
        # block, so all chunks of one (node, g-tile) must run back to back.
        c = pl.program_id(2)

        @pl.when(c == 0)
        def _init():
            ot_ref[...] = jnp.zeros_like(ot_ref)
            oc_ref[...] = jnp.zeros_like(oc_ref)

        j0 = c * Cb
        for blk_ref, rows_ref, out_ref, R in (
            (lt_ref, rt_ref, ot_ref, Rtp),
            (lc_ref, rc_ref, oc_ref, Rcp),
        ):
            rows = rows_ref[...]
            rel = rows - j0
            hit = (rel >= 0) & (rel < Cb)
            relc = jnp.clip(rel, 0, Cb - 1)
            idx_full = jnp.concatenate(
                [relc, jnp.zeros((Cb - R, tile), _I32)], axis=0)
            # Widen to i32 for the dynamic_gather, narrow back after: Mosaic's
            # gather support is solid on 32-bit lanes; the cast is VMEM-local.
            vals = jnp.take_along_axis(
                blk_ref[...].astype(_I32), idx_full, axis=0)[:R]
            out_ref[...] = jnp.where(hit, vals.astype(out_ref.dtype),
                                     out_ref[...])

    grid = (N, G // tile, n_chunks)
    call = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((Cb, tile), lambda n, i, c: (n * n_chunks + c, i)),
            pl.BlockSpec((Cb, tile), lambda n, i, c: (n * n_chunks + c, i)),
            pl.BlockSpec((Rtp, tile), lambda n, i, c: (n, i)),
            pl.BlockSpec((Rcp, tile), lambda n, i, c: (n, i)),
        ],
        out_specs=[
            pl.BlockSpec((Rtp, tile), lambda n, i, c: (n, i)),
            pl.BlockSpec((Rcp, tile), lambda n, i, c: (n, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N * Rtp, G), ldt),
            jax.ShapeDtypeStruct((N * Rcp, G), ldt),
        ],
        interpret=interpret,
    )
    if Rtp == Rt and Rcp == Rc:
        return call

    def padded_call(lt, lc, rows_t, rows_c):
        def pad(r, R, Rp):
            r = r.reshape(N, R, G)
            z = jnp.zeros((N, Rp - R, G), _I32)
            return jnp.concatenate([r, z], axis=1).reshape(N * Rp, G)

        vt, vc = call(lt, lc, pad(rows_t, Rt, Rtp), pad(rows_c, Rc, Rcp))
        return (vt.reshape(N, Rtp, G)[:, :Rt].reshape(N * Rt, G),
                vc.reshape(N, Rcp, G)[:, :Rc].reshape(N * Rc, G))

    return padded_call
