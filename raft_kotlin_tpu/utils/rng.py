"""Canonical randomness derivation — the single source of truth for every random draw.

The reference draws from JVM global RNGs (java.util.Random in Commons.kt:33-34, timer
jitter Commons.kt:23, backoff RaftServer.kt:221), which is irreproducible. Here every
draw is a counted threefry evaluation keyed by (kind, group, node, per-node counter), so
the scalar CPU oracle and the vectorized TPU kernel — and any backend, any device —
see bit-identical values. See SEMANTICS.md §4.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_threefry_partitionable", True)

KIND_TIMEOUT = 0
KIND_BACKOFF = 1
KIND_FAULT = 2
KIND_CRASH = 3
KIND_RESTART = 4
KIND_LINK_FAIL = 5
KIND_LINK_HEAL = 6
KIND_DELAY = 7
# §20 serving path (SEMANTICS.md §20): per-tick client draws. CLIENT keys
# the generated write commands' slot choices, READ keys the read-path key
# choices — both evaluated via the kernel-twin primitives below (kt_*), so
# the in-scan generator and a host-eager recompute produce identical bits
# (the device-generator ≡ host-queue equality theorem).
KIND_CLIENT = 8
KIND_READ = 9

# Scenario-bank sampling kinds (SEMANTICS.md §12): one counted-threefry
# stream per channel, keyed by (farm_seed, channel kind, universe_id) — a
# universe's parameters depend on its id alone, never on the batch shape,
# so any batch containing universe u reproduces exactly u's lattice.
# Disjoint from the per-tick kinds above (different base key anyway — the
# farm_seed, not the run seed — but kept disjoint for greppability).
SCEN_KIND_DROP = 32
SCEN_KIND_CRASH = 33
SCEN_KIND_RESTART = 34
SCEN_KIND_LINK_FAIL = 35
SCEN_KIND_LINK_HEAL = 36
SCEN_KIND_DELAY_LO = 37
SCEN_KIND_DELAY_HI = 38
SCEN_KIND_PART_KIND = 39
SCEN_KIND_PART_CUT = 40
SCEN_KIND_PART_SRC = 41
SCEN_KIND_PART_DST = 42
SCEN_KIND_PART_PERIOD = 43
SCEN_KIND_PART_DUTY = 44
SCEN_KIND_PART_PHASE = 45
SCEN_KIND_EL_LO = 46
SCEN_KIND_EL_HI = 47
SCEN_KIND_LIFE = 48
# §20 client-stream channels (the serving path's load-generator shape —
# per-group writes/tick, reads/tick, and hot-key weight in permille).
SCEN_KIND_CLIENT_RATE = 49
SCEN_KIND_CLIENT_READ = 50
SCEN_KIND_CLIENT_HOT = 51

# Event probabilities live in a 23-bit integer domain: jax's f32 uniform is
# exactly (bits >> 9) * 2^-23, so `bernoulli(key, p) == (bits(key) >> 9) <
# p_threshold(p)` bit-for-bit — the one integer-exact event path shared by
# scalar configs and per-group scenario banks (tests/test_fuzz.py pins the
# equivalence against jax.random.bernoulli itself, so a jax upgrade that
# changes the uniform bit derivation fails loudly).
P_BITS = 23
P_SHIFT = 32 - P_BITS


def p_threshold(p: float) -> int:
    """The 23-bit threshold t with `uniform(key) < f32(p)  <=>
    (bits(key) >> 9) < t`, exact: f32(p) * 2^23 is exact in double
    (24-bit significand times a power of two), and ceil counts the
    uniform lattice points strictly below p."""
    p32 = float(np.float32(p)) if p == p else 0.0  # NaN -> 0
    return max(0, min(math.ceil(p32 * (1 << P_BITS)), 1 << P_BITS))


def base_key(seed: int) -> jax.Array:
    return jax.random.key(seed)


def _key(base: jax.Array, kind, g, n, ctr) -> jax.Array:
    k = jax.random.fold_in(base, kind)
    k = jax.random.fold_in(k, g)
    k = jax.random.fold_in(k, n)
    k = jax.random.fold_in(k, ctr)
    return k


def draw_uniform(base: jax.Array, kind, g, n, ctr, lo: int, hi: int) -> jax.Array:
    """One scalar draw, uniform on the inclusive range [lo, hi].

    Inclusivity matches Kotlin's `(a..b).random()` (reference Commons.kt:33-34).
    """
    return jax.random.randint(_key(base, kind, g, n, ctr), (), lo, hi + 1, dtype=jnp.int32)


def draw_uniform_grid(
    base: jax.Array, kind: int, ctrs: jax.Array, lo, hi
) -> jax.Array:
    """Vectorized draws over a (G, N) counter grid; element [g, i] equals
    draw_uniform(base, kind, g, n=i+1, ctrs[g, i], lo, hi) exactly. Bounds
    may be Python ints or arrays broadcastable to ctrs.shape (per-group
    timeout windows pass (G, 1)); randint's bit stream depends only on the
    bound VALUES, so array bounds equal to a scalar reproduce the scalar
    path exactly (same precedent as delay_mask's per-group windows)."""
    G, N = ctrs.shape
    g_idx = jnp.arange(G, dtype=jnp.int32)[:, None].repeat(N, axis=1)
    n_idx = jnp.arange(1, N + 1, dtype=jnp.int32)[None, :].repeat(G, axis=0)
    lo = jnp.broadcast_to(jnp.asarray(lo, jnp.int32), ctrs.shape)
    hi = jnp.broadcast_to(jnp.asarray(hi, jnp.int32), ctrs.shape)
    f = lambda g, n, c, a, b: jax.random.randint(
        _key(base, kind, g, n, c), (), a, b + 1, dtype=jnp.int32)
    return jax.vmap(jax.vmap(f))(g_idx, n_idx, ctrs, lo, hi)


def grid_keys(base: jax.Array, kind: int, G: int, N: int) -> jax.Array:
    """(G, N) array of the STATIC key prefix of §4's derivation:
    grid_keys[g, i] == fold_in(fold_in(fold_in(base, kind), g), i+1).

    fold_in composes one argument at a time, so folding the per-draw counter into
    grid_keys[g, i] afterwards yields bit-identical keys to the full chain — this
    precomputes the 3 static fold_ins once per simulation instead of per draw (the
    hot tick kernel then pays 1 fold_in + 1 randint per draw instead of 4 + 1).
    """
    g_idx = jnp.arange(G, dtype=jnp.int32)
    n_idx = jnp.arange(1, N + 1, dtype=jnp.int32)
    kk = jax.random.fold_in(base, kind)
    f = lambda g, n: jax.random.fold_in(jax.random.fold_in(kk, g), n)
    return jax.vmap(lambda g: jax.vmap(lambda n: f(g, n))(n_idx))(g_idx)


def draw_uniform_keyed(keys: jax.Array, ctrs: jax.Array, lo, hi) -> jax.Array:
    """Inclusive-uniform draws from precomputed static-prefix keys (see grid_keys);
    element [..] == draw_uniform(base, kind, g, n, ctrs[..], lo, hi) exactly.
    Shape-polymorphic: keys and ctrs must have equal shapes. Bounds may be
    ints or arrays broadcastable to ctrs.shape (per-group timeout windows) —
    randint's bits depend only on the bound VALUES, so an array bound equal
    to the scalar is bit-identical to the scalar path."""
    lo = jnp.broadcast_to(jnp.asarray(lo, jnp.int32), ctrs.shape)
    hi = jnp.broadcast_to(jnp.asarray(hi, jnp.int32), ctrs.shape)
    f = lambda k, c, a, b: jax.random.randint(
        jax.random.fold_in(k, c), (), a, b + 1, dtype=jnp.int32
    )
    for _ in range(ctrs.ndim):
        f = jax.vmap(f)
    return f(keys, ctrs, lo, hi)


def draw_uniform_counters(
    base: jax.Array, kind: int, g: int, n: int, ctrs, lo: int, hi: int
) -> jax.Array:
    """Vectorized draws for one (group, node) over an array of counters; element [k]
    equals draw_uniform(base, kind, g, n, ctrs[k], lo, hi) exactly. Used by the oracle's
    predraw tables — same derivation as the kernel's per-tick draws."""
    return jax.vmap(lambda c: draw_uniform(base, kind, g, n, c, lo, hi))(ctrs)


def _event_bits(base: jax.Array, kind: int, tick, shape: tuple) -> jax.Array:
    """The 23-bit uniform lattice draw behind every shaped event mask —
    identical bits to what jax's bernoulli/uniform consumes at this key."""
    k = jax.random.fold_in(jax.random.fold_in(base, kind), tick)
    return jax.random.bits(k, shape, dtype=jnp.uint32) >> P_SHIFT


def _thresh_bcast(thresh, shape: tuple) -> jax.Array:
    """A scalar or per-group (G,) threshold broadcast against a (G, ...)
    event shape, as uint32."""
    t = jnp.asarray(thresh).astype(jnp.uint32)
    if t.ndim == 1:
        t = t.reshape(t.shape + (1,) * (len(shape) - 1))
    return t


def edge_ok_mask(base: jax.Array, tick, shape: tuple, p_drop: float,
                 thresh=None) -> jax.Array:
    """(G, N, N) boolean mask for tick `tick`: element [g, s-1, r-1] is True iff the
    directed message s -> r in group g survives this tick. One shaped draw per tick,
    shared verbatim by oracle and kernel (SEMANTICS.md §4).

    `thresh` (per-group (G,) int32 23-bit thresholds — the scenario bank's
    drop channel, SEMANTICS.md §12) overrides the scalar probability; the
    scalar path routes through p_threshold onto the SAME integer compare,
    bit-identical to the historical bernoulli form (see p_threshold)."""
    if thresh is None:
        if p_drop <= 0.0:
            return jnp.ones(shape, dtype=bool)
        thresh = p_threshold(p_drop)
    bits = _event_bits(base, KIND_FAULT, tick, shape)
    return bits >= _thresh_bcast(thresh, shape)


def delay_mask(base: jax.Array, tick, shape: tuple, lo: int, hi: int,
               lo_g=None, hi_g=None) -> jax.Array:
    """(G, N, N) int32 of per-directed-pair message delays for sends at tick `tick`,
    uniform on [lo, hi] inclusive (SEMANTICS.md §10). Element [g, s-1, r-1] is the
    delay of the exchange s sends to r this tick. One shaped draw per tick, shared
    verbatim by oracle, kernel, and native engine — same pattern as edge_ok_mask.

    `lo_g`/`hi_g` (per-group (G,) int32 — the scenario bank's delay
    windows) override the scalar bounds per group; jax's randint broadcasts
    array bounds elementwise over the same drawn bits, so equal per-group
    bounds are bit-identical to the scalar call (tests/test_fuzz.py)."""
    if lo_g is None and lo == hi:
        return jnp.full(shape, lo, dtype=jnp.int32)
    k = jax.random.fold_in(jax.random.fold_in(base, KIND_DELAY), tick)
    if lo_g is not None:
        ext = (1,) * (len(shape) - 1)
        return jax.random.randint(
            k, shape, lo_g.reshape(lo_g.shape + ext),
            hi_g.reshape(hi_g.shape + ext) + 1, dtype=jnp.int32)
    return jax.random.randint(k, shape, lo, hi + 1, dtype=jnp.int32)


def event_mask(base: jax.Array, kind: int, tick, shape: tuple, p: float,
               thresh=None) -> jax.Array:
    """Shaped boolean event draw for tick `tick` (True = event fires). One draw per
    (kind, tick), shared verbatim by oracle and kernel — the fault-event analogue of
    `edge_ok_mask` (SEMANTICS.md §9: crash/restart/link-fail/link-heal events).
    `thresh` selects the per-group scenario-bank channel (see edge_ok_mask)."""
    if thresh is None:
        if p <= 0.0:
            return jnp.zeros(shape, dtype=bool)
        thresh = p_threshold(p)
    bits = _event_bits(base, kind, tick, shape)
    return bits < _thresh_bcast(thresh, shape)


# ---------------------------------------------------------------------------
# Scenario bank (SEMANTICS.md §12): per-group fault lattices, delay windows
# and scripted partition programs, sampled from a counted threefry stream
# keyed by (farm_seed, channel, universe_id).

from raft_kotlin_tpu.utils.config import (  # noqa: E402  (no import cycle:
    PART_ASYM, PART_LEADER, PART_NONE, PART_SPLIT)  # config imports nothing)

# Bank key -> aux consumer, for reference. All values are (G,) int32:
#   drop_t/crash_t/restart_t/link_fail_t/link_heal_t  23-bit thresholds
#   delay_lo/delay_hi                                 per-group §10 windows
#   part_kind (PART_* code) / part_cut (split block size) / part_src,
#   part_dst (asym directed edge) / part_period, part_duty, part_phase
#   (the flapping window: active iff (tick + phase) % period < duty)
THRESHOLD_CHANNELS = {
    "drop_t": ("drop_max", "p_drop", SCEN_KIND_DROP),
    "crash_t": ("crash_max", "p_crash", SCEN_KIND_CRASH),
    "restart_t": ("restart_max", "p_restart", SCEN_KIND_RESTART),
    "link_fail_t": ("link_fail_max", "p_link_fail", SCEN_KIND_LINK_FAIL),
    "link_heal_t": ("link_heal_max", "p_link_heal", SCEN_KIND_LINK_HEAL),
}
PARTITION_KEYS = ("part_kind", "part_cut", "part_src", "part_dst",
                  "part_period", "part_duty", "part_phase")


def _scen_draw(fkey, kind: int, uids, lo, hi):
    """(G,) int32, element u = the counted inclusive-uniform draw for
    universe uids[u] on [lo[u], hi[u]] (bounds scalars or (G,) arrays) —
    keyed by (farm_seed, kind, universe_id) only, never by batch shape."""
    kk = jax.random.fold_in(fkey, kind)
    lo = jnp.broadcast_to(jnp.asarray(lo, jnp.int32), uids.shape)
    hi = jnp.broadcast_to(jnp.asarray(hi, jnp.int32), uids.shape)
    f = lambda u, a, b: jax.random.randint(
        jax.random.fold_in(kk, u), (), a, b + 1, dtype=jnp.int32)
    return jax.vmap(f)(uids, lo, hi)


def sample_scenario_bank(cfg, uids=None) -> dict:
    """The ScenarioBank for `cfg` (cfg.scenario must be set): a dict of
    (n_groups,) int32 arrays — see the key table above. Pure jnp (traceable;
    ops/tick.make_rng computes it into the rng operand). Channel keys are
    PRESENT iff the channel is active, and that presence is what compiles
    the corresponding engine paths in (ops/tick.make_flags reads the spec).

    `uids` optionally overrides the default universe-id row
    (universe_base + arange(G)) with an explicit (G,) int32 array — the
    continuous scheduler's admission hook (SEMANTICS.md §19): a retired
    lane's bank row is re-sampled under a fresh serial while every other
    row keeps its id, and because draws are keyed by (farm_seed, kind,
    universe_id) only, the surviving rows are bit-identical to the static
    batch that would have held them.

    degenerate=True builds the bank from the config's own scalar fault
    fields instead of sampling — all groups identical, every active scalar
    channel routed through the bank code path — the provable
    bit-identical-to-scalar case (tests/test_fuzz.py)."""
    spec = cfg.scenario
    assert spec is not None, "sample_scenario_bank needs cfg.scenario"
    G, N = cfg.n_groups, cfg.n_nodes
    bank: dict = {}
    if spec.degenerate:
        for key, (_mx, scalar, _kind) in THRESHOLD_CHANNELS.items():
            p = getattr(cfg, scalar)
            if p > 0:
                bank[key] = jnp.full((G,), p_threshold(p), jnp.int32)
        if cfg.delay_lo < cfg.delay_hi:
            bank["delay_lo"] = jnp.full((G,), cfg.delay_lo, jnp.int32)
            bank["delay_hi"] = jnp.full((G,), cfg.delay_hi, jnp.int32)
        return bank
    fkey = jax.random.key(spec.farm_seed)
    if uids is None:
        uids = spec.universe_base + jnp.arange(G, dtype=jnp.int32)
    else:
        uids = jnp.asarray(uids, jnp.int32)
        assert uids.shape == (G,), uids.shape
    for key, (mx_name, _scalar, kind) in THRESHOLD_CHANNELS.items():
        mx = getattr(spec, mx_name)
        if mx > 0:
            bank[key] = _scen_draw(fkey, kind, uids, 0, p_threshold(mx))
    if spec.delay_windows:
        lo = _scen_draw(fkey, SCEN_KIND_DELAY_LO, uids,
                        cfg.delay_lo, cfg.delay_hi)
        bank["delay_lo"] = lo
        bank["delay_hi"] = _scen_draw(fkey, SCEN_KIND_DELAY_HI, uids,
                                      lo, cfg.delay_hi)
    if spec.partitions:
        codes = {"split": PART_SPLIT, "asym": PART_ASYM,
                 "leader": PART_LEADER}
        table = jnp.asarray(
            (PART_NONE,) + tuple(codes[k] for k in spec.partitions),
            jnp.int32)
        idx = _scen_draw(fkey, SCEN_KIND_PART_KIND, uids,
                         0, len(spec.partitions))
        bank["part_kind"] = jnp.take(table, idx)
        bank["part_cut"] = _scen_draw(fkey, SCEN_KIND_PART_CUT, uids,
                                      1, max(1, N - 1))
        src = _scen_draw(fkey, SCEN_KIND_PART_SRC, uids, 1, N)
        dst0 = _scen_draw(fkey, SCEN_KIND_PART_DST, uids, 1, max(1, N - 1))
        bank["part_src"] = src
        # dst uniform over [1, N] \ {src} (spec validation pins N >= 2).
        bank["part_dst"] = dst0 + (dst0 >= src).astype(jnp.int32)
        period = _scen_draw(fkey, SCEN_KIND_PART_PERIOD, uids,
                            spec.part_period_lo, spec.part_period_hi)
        bank["part_period"] = period
        bank["part_duty"] = _scen_draw(fkey, SCEN_KIND_PART_DUTY, uids,
                                       1, period)
        bank["part_phase"] = _scen_draw(fkey, SCEN_KIND_PART_PHASE, uids,
                                        0, period - 1)
    if spec.timeout_windows:
        # Per-group randomized election-timeout windows (§19): each
        # universe gets its own [el_lo, el_hi] sub-range of the config's
        # window — lo uniform over the full window, hi uniform over
        # [lo, cfg.el_hi] (same nesting as the delay windows above).
        lo = _scen_draw(fkey, SCEN_KIND_EL_LO, uids, cfg.el_lo, cfg.el_hi)
        bank["el_lo"] = lo
        bank["el_hi"] = _scen_draw(fkey, SCEN_KIND_EL_HI, uids,
                                   lo, cfg.el_hi)
    if spec.life_hi > 0:
        # Per-group lifetime (ticks until horizon-reached retirement) —
        # the continuous scheduler's heterogeneous-lifetime channel.
        bank["life"] = _scen_draw(fkey, SCEN_KIND_LIFE, uids,
                                  spec.life_lo, spec.life_hi)
    # §20 client-stream channels (the serving load generator's per-group
    # workload shape — ops/serving.py reads these rows).
    if spec.client_rate_max > 0:
        bank["client_rate"] = _scen_draw(fkey, SCEN_KIND_CLIENT_RATE, uids,
                                         1, spec.client_rate_max)
    if spec.client_read_max > 0:
        bank["client_read"] = _scen_draw(fkey, SCEN_KIND_CLIENT_READ, uids,
                                         1, spec.client_read_max)
    if spec.client_hot_max > 0:
        bank["client_hot"] = _scen_draw(fkey, SCEN_KIND_CLIENT_HOT, uids,
                                        0, spec.client_hot_max)
    return bank


def scenario_active(scen: dict, tick):
    """The §12 flapping window: True where a group's partition program is
    ACTIVE at `tick` — (tick + phase) % period < duty. THE one copy of the
    window formula (scenario_link_down and the native engine's host-side
    leader_iso channel both evaluate exactly this); `tick` may be a scalar
    or a broadcastable array of ticks."""
    return ((tick + scen["part_phase"]) % scen["part_period"]) \
        < scen["part_duty"]


def scenario_link_down(scen: dict, tick, leader_gn, N: int, xp=jnp):
    """The per-tick scheduled-partition mask: (G, N, N) bool, True where
    the directed edge s -> r is DOWN this tick under the group's partition
    program (SEMANTICS.md §12). Pure integer/boolean arithmetic — `xp` is
    jnp for the kernels and np for the scalar oracles, so every
    implementation evaluates the SAME function.

    Programs (scen["part_kind"], PART_* codes), gated by the flapping
    window active = (tick + phase) % period < duty:
    - PART_SPLIT:  clean split {1..cut} vs {cut+1..N}; cross edges down
      both ways.
    - PART_ASYM:   the single directed edge src -> dst down.
    - PART_LEADER: every edge touching a node that was a LIVE LEADER at
      tick start (`leader_gn`: (G, N) bool; pre-phase-F state) down.
    Self-edges are never partitioned (a node always reaches itself)."""
    kind = scen["part_kind"]
    G = kind.shape[0]
    active = scenario_active(scen, tick)
    ids = xp.arange(1, N + 1, dtype=kind.dtype)
    s_id, r_id = ids[None, :, None], ids[None, None, :]
    k = kind[:, None, None]
    cut = scen["part_cut"][:, None, None]
    split = (s_id <= cut) != (r_id <= cut)
    asym = (s_id == scen["part_src"][:, None, None]) \
        & (r_id == scen["part_dst"][:, None, None])
    if leader_gn is None:
        ldr = xp.zeros((G, N, N), dtype=bool)
    else:
        lg = leader_gn != 0
        ldr = lg[:, :, None] | lg[:, None, :]
    down = ((k == PART_SPLIT) & split) | ((k == PART_ASYM) & asym) \
        | ((k == PART_LEADER) & ldr)
    return down & active[:, None, None] & (s_id != r_id)


def scen_layout(cfg) -> tuple:
    """The ordered tuple of ScenarioBank keys `sample_scenario_bank(cfg)`
    produces — deterministic from the config alone, so an in-kernel launch
    can lay its resident (G,) scenario rows out at BUILD time and the
    runtime bank (which rides the rng operand) packs into the same slots.
    Mirrors sample_scenario_bank's presence rules exactly (a new channel
    there must be added here; tests/test_inkernel_aux.py pins the two
    equal over the fuzz specs)."""
    spec = getattr(cfg, "scenario", None)
    if spec is None:
        return ()
    keys = []
    if spec.degenerate:
        for key, (_mx, scalar, _kind) in THRESHOLD_CHANNELS.items():
            if getattr(cfg, scalar) > 0:
                keys.append(key)
        if cfg.delay_lo < cfg.delay_hi:
            keys += ["delay_lo", "delay_hi"]
        return tuple(keys)
    for key, (mx_name, _scalar, _kind) in THRESHOLD_CHANNELS.items():
        if getattr(spec, mx_name) > 0:
            keys.append(key)
    if spec.delay_windows:
        keys += ["delay_lo", "delay_hi"]
    if spec.partitions:
        keys += list(PARTITION_KEYS)
    if spec.timeout_windows:
        keys += ["el_lo", "el_hi"]
    if spec.life_hi > 0:
        keys += ["life"]
    if spec.client_rate_max > 0:
        keys += ["client_rate"]
    if spec.client_read_max > 0:
        keys += ["client_read"]
    if spec.client_hot_max > 0:
        keys += ["client_hot"]
    return tuple(keys)


def apply_warmup_faults(spec, cmd_node: int, tick, crash, restart, xp=jnp):
    """§15 warmup-down post-processing of the §9 crash/restart event masks
    (canonical (G, N) orientation, 0-based tick). For warmup_down = W > 0
    every node except cmd_node is held crashed on ticks t < W (crash
    asserted, random restarts suppressed) and restarted at exactly
    t == W; cmd_node and all other channels are untouched. Deterministic
    integer/boolean arithmetic on the already-drawn masks — no draws are
    consumed, so the RNG streams stay aligned and the XLA/Pallas kernels,
    the Python oracle and the native engine apply the SAME rule (`xp` is
    jnp for the kernels, np for the host-side builders)."""
    W = 0 if spec is None else getattr(spec, "warmup_down", 0)
    if not W:
        return crash, restart
    N = crash.shape[-1]
    notcmd = (xp.arange(N) != (cmd_node - 1))[None, :]
    hold = (tick < W) & notcmd
    rejoin = (tick == W) & notcmd
    return crash | hold, (restart & ~hold) | rejoin


# ---------------------------------------------------------------------------
# Kernel twin (SEMANTICS.md §17): counter-based threefry2x32 as plain int32
# lattice arithmetic — evaluable inside a Mosaic kernel (adds wrap, xor,
# shifts; no jax.random machinery) AND on the host, where the unit pins in
# tests/test_inkernel_aux.py hold every kt_* primitive bit-identical to the
# jax.random derivation the host channels above consume. The channel
# functions above stay THE single semantic source; these twins re-derive
# the same bits from (key words, linear lattice index) so the megakernel
# can draw its own aux (ops/pallas_tick aux_source="inkernel") instead of
# re-reading a staged HBM stream. Counter convention (pinned by the tests,
# matching jax's threefry_partitionable u32 path on shaped draws):
# bits(key, shape)[..flat index i..] == bitcast_u32(b0 ^ b1) where
# (b0, b1) = kt_block(k0, k1, 0, i) over the key's two 32-bit words.

_KT_PARITY = np.int32(0x1BD11BDA)
_KT_ROT = ((13, 15, 26, 6), (17, 29, 16, 24))
# Key-schedule injections after each 4-round group: (ks index for x0,
# ks index for x1, round-group counter added into x1).
_KT_INJ = ((1, 2, 1), (2, 0, 2), (0, 1, 3), (1, 2, 4), (2, 0, 5))


def kt_key_words(keys):
    """A (typed) jax.random key array -> its two int32 key words, shape
    preserved. Host-side only (jax.random.key_data); the words then travel
    into the kernel as plain int32 planes."""
    d = jax.random.key_data(keys)
    w = jax.lax.bitcast_convert_type(d, jnp.int32)
    return w[..., 0], w[..., 1]


def _kt_rotl(x, r: int):
    return jax.lax.bitwise_or(
        jax.lax.shift_left(x, np.int32(r)),
        jax.lax.shift_right_logical(x, np.int32(32 - r)))


def kt_block(k0, k1, c0, c1):
    """One threefry2x32 block (20 rounds) on int32 words — bit-identical to
    jax's threefry2x32 on the same (key, counter) words (wrapping int32 adds
    == u32 adds). All four operands broadcast; returns (x0, x1)."""
    ks2 = jax.lax.bitwise_xor(jax.lax.bitwise_xor(k0, k1), _KT_PARITY)
    ks = (k0, k1, ks2)
    x0 = c0 + ks[0]
    x1 = c1 + ks[1]
    for g in range(5):
        for r in _KT_ROT[g % 2]:
            x0 = x0 + x1
            x1 = _kt_rotl(x1, r)
            x1 = jax.lax.bitwise_xor(x1, x0)
        a, b, d = _KT_INJ[g]
        x0 = x0 + ks[a]
        x1 = x1 + ks[b] + np.int32(d)
    return x0, x1


def kt_fold(k0, k1, d):
    """fold_in twin: key words of jax.random.fold_in(key, d) from the words
    of `key` — one block at counter (0, d)."""
    d = jnp.asarray(d, jnp.int32)
    return kt_block(k0, k1, jnp.zeros_like(d), d)


def kt_bits32(k0, k1, idx):
    """bits(key, shape, uint32) twin at flat lattice index `idx` (row-major
    over the host shape), as the int32 BIT PATTERN of the u32 draw."""
    b0, b1 = kt_block(k0, k1, jnp.zeros_like(idx), idx)
    return jax.lax.bitwise_xor(b0, b1)


def kt_bits23(k0, k1, idx):
    """_event_bits twin: the 23-bit uniform lattice (bits >> P_SHIFT) behind
    every event mask, nonneg in int32 so signed compares against the §12
    thresholds are exact."""
    return jax.lax.shift_right_logical(kt_bits32(k0, k1, idx),
                                       np.int32(P_SHIFT))


def _kt_umod(x, s):
    """Unsigned x mod s evaluated on int32 bit patterns (s > 0 int32):
    (x mod s) == ((x & 0x7fffffff) mod s + sign_bit * (2^31 mod s)) mod s."""
    lo = jnp.remainder(jax.lax.bitwise_and(x, np.int32(0x7FFFFFFF)), s)
    sign = jax.lax.bitwise_and(
        jax.lax.shift_right_logical(x, np.int32(31)), np.int32(1))
    top = jnp.remainder(
        np.int32(2) * jnp.remainder(np.int32(2 ** 30), s), s)
    return jnp.remainder(lo + sign * top, s)


def kt_randint(k0, k1, idx, lo, span):
    """jax.random.randint twin on [lo, lo+span) at flat lattice index `idx`
    over the (already tick/counter-folded) key words: jax draws two 32-bit
    lattices (keys fold_in(key, 0) / fold_in(key, 1)) and combines them as
    (hi % span * (2^32 % span) + lo % span) % span in unsigned arithmetic.
    `lo`/`span` are int32 scalars or broadcastable arrays (the §12 per-group
    delay windows); span must satisfy span^2 < 2^31 (every config window
    does — the unit pins cover the per-group array-bounds case)."""
    z = jnp.zeros_like(idx)
    lo = jnp.asarray(lo, jnp.int32)
    span = jnp.asarray(span, jnp.int32)
    ka0, ka1 = kt_fold(k0, k1, 0)
    kb0, kb1 = kt_fold(k0, k1, 1)
    h0, h1 = kt_block(ka0, ka1, z, idx)
    l0, l1 = kt_block(kb0, kb1, z, idx)
    hb = jax.lax.bitwise_xor(h0, h1)
    lb = jax.lax.bitwise_xor(l0, l1)
    mult = jnp.remainder(np.int32(2 ** 16), span)
    mult = jnp.remainder(mult * mult, span)
    off = jnp.remainder(_kt_umod(hb, span) * mult + _kt_umod(lb, span), span)
    return lo + off


def kt_draw_uniform(k0, k1, ctr, lo, hi):
    """draw_uniform_keyed twin: the per-(node, group) counted draw on the
    inclusive [lo, hi] window — fold the live counter into the static-prefix
    key words (grid_keys), then the scalar-shape randint (lattice index 0)."""
    c0, c1 = kt_fold(k0, k1, ctr)
    return kt_randint(c0, c1, jnp.zeros_like(ctr), lo,
                      jnp.asarray(hi, jnp.int32) - lo + 1)


def kt_event_key(k0, k1, kind: int, tick):
    """The per-(kind, tick) channel key words: fold_in(fold_in(base, kind),
    tick) — the static half of _event_bits, shared by every lattice the
    channel draws this tick."""
    e0, e1 = kt_fold(k0, k1, kind)
    return kt_fold(e0, e1, tick)


def kt_edge_ok_mask(k0, k1, tick, idx, thresh):
    """edge_ok_mask twin at flat (g*N*N + (s-1)*N + (r-1)) lattice index:
    True iff the directed message survives — bits23 >= thresh, the same
    integer-exact compare as the host (thresh scalar or per-lane row).
    The p_drop <= 0 fast path (all-ones, no draw) is the CALLER's, decided
    at kernel build time exactly like edge_ok_mask's early return."""
    e0, e1 = kt_event_key(k0, k1, KIND_FAULT, tick)
    return kt_bits23(e0, e1, idx) >= thresh


def kt_event_mask(k0, k1, kind: int, tick, idx, thresh):
    """event_mask twin (crash/restart/link-fail/link-heal): True = event
    fires — bits23 < thresh. The p <= 0 fast path (all-zeros) is the
    caller's, as in event_mask."""
    e0, e1 = kt_event_key(k0, k1, kind, tick)
    return kt_bits23(e0, e1, idx) < thresh


def kt_delay_mask(k0, k1, tick, idx, lo, hi):
    """delay_mask twin at the pair lattice index: the [lo, hi]-inclusive
    per-directed-pair delay (lo/hi scalars or the §12 per-group rows).
    The lo == hi scalar fast path (constant, no draw) is the caller's."""
    d0, d1 = kt_event_key(k0, k1, KIND_DELAY, tick)
    return kt_randint(d0, d1, idx, lo,
                      jnp.asarray(hi, jnp.int32) - lo + 1)


def kt_part_down(kind, cut, src, dst, active, s_id, r_id,
                 lead_s=None, lead_r=None):
    """scenario_link_down twin on the kernel's pair-lattice orientation:
    every operand pre-broadcast against the (N*N, lanes) block — scen rows
    (1, lanes), s_id/r_id (N*N, 1) or (N*N, lanes), lead_s/lead_r the
    live-leader value of the edge's sender/receiver (the in-kernel
    evaluation that lifts the fused leader-iso fallback: the caller builds
    them from the CURRENT VMEM role/up planes, which at each fused tick
    start equal the staged path's pre-tick state). Same program, same
    flapping gate (`active` = scenario_active at this tick), same
    self-edge exemption as the host function."""
    split = (s_id <= cut) != (r_id <= cut)
    asym = (s_id == src) & (r_id == dst)
    if lead_s is None:
        ldr = jnp.zeros(jnp.broadcast_shapes(s_id.shape, kind.shape), bool)
    else:
        ldr = (lead_s != 0) | (lead_r != 0)
    down = ((kind == PART_SPLIT) & split) | ((kind == PART_ASYM) & asym) \
        | ((kind == PART_LEADER) & ldr)
    return down & active & (s_id != r_id)
