"""Canonical randomness derivation — the single source of truth for every random draw.

The reference draws from JVM global RNGs (java.util.Random in Commons.kt:33-34, timer
jitter Commons.kt:23, backoff RaftServer.kt:221), which is irreproducible. Here every
draw is a counted threefry evaluation keyed by (kind, group, node, per-node counter), so
the scalar CPU oracle and the vectorized TPU kernel — and any backend, any device —
see bit-identical values. See SEMANTICS.md §4.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

jax.config.update("jax_threefry_partitionable", True)

KIND_TIMEOUT = 0
KIND_BACKOFF = 1
KIND_FAULT = 2
KIND_CRASH = 3
KIND_RESTART = 4
KIND_LINK_FAIL = 5
KIND_LINK_HEAL = 6
KIND_DELAY = 7


def base_key(seed: int) -> jax.Array:
    return jax.random.key(seed)


def _key(base: jax.Array, kind, g, n, ctr) -> jax.Array:
    k = jax.random.fold_in(base, kind)
    k = jax.random.fold_in(k, g)
    k = jax.random.fold_in(k, n)
    k = jax.random.fold_in(k, ctr)
    return k


def draw_uniform(base: jax.Array, kind, g, n, ctr, lo: int, hi: int) -> jax.Array:
    """One scalar draw, uniform on the inclusive range [lo, hi].

    Inclusivity matches Kotlin's `(a..b).random()` (reference Commons.kt:33-34).
    """
    return jax.random.randint(_key(base, kind, g, n, ctr), (), lo, hi + 1, dtype=jnp.int32)


def draw_uniform_grid(
    base: jax.Array, kind: int, ctrs: jax.Array, lo: int, hi: int
) -> jax.Array:
    """Vectorized draws over a (G, N) counter grid; element [g, i] equals
    draw_uniform(base, kind, g, n=i+1, ctrs[g, i], lo, hi) exactly."""
    G, N = ctrs.shape
    g_idx = jnp.arange(G, dtype=jnp.int32)[:, None].repeat(N, axis=1)
    n_idx = jnp.arange(1, N + 1, dtype=jnp.int32)[None, :].repeat(G, axis=0)
    f = lambda g, n, c: draw_uniform(base, kind, g, n, c, lo, hi)
    return jax.vmap(jax.vmap(f))(g_idx, n_idx, ctrs)


def grid_keys(base: jax.Array, kind: int, G: int, N: int) -> jax.Array:
    """(G, N) array of the STATIC key prefix of §4's derivation:
    grid_keys[g, i] == fold_in(fold_in(fold_in(base, kind), g), i+1).

    fold_in composes one argument at a time, so folding the per-draw counter into
    grid_keys[g, i] afterwards yields bit-identical keys to the full chain — this
    precomputes the 3 static fold_ins once per simulation instead of per draw (the
    hot tick kernel then pays 1 fold_in + 1 randint per draw instead of 4 + 1).
    """
    g_idx = jnp.arange(G, dtype=jnp.int32)
    n_idx = jnp.arange(1, N + 1, dtype=jnp.int32)
    kk = jax.random.fold_in(base, kind)
    f = lambda g, n: jax.random.fold_in(jax.random.fold_in(kk, g), n)
    return jax.vmap(lambda g: jax.vmap(lambda n: f(g, n))(n_idx))(g_idx)


def draw_uniform_keyed(keys: jax.Array, ctrs: jax.Array, lo: int, hi: int) -> jax.Array:
    """Inclusive-uniform draws from precomputed static-prefix keys (see grid_keys);
    element [..] == draw_uniform(base, kind, g, n, ctrs[..], lo, hi) exactly.
    Shape-polymorphic: keys and ctrs must have equal shapes."""
    f = lambda k, c: jax.random.randint(
        jax.random.fold_in(k, c), (), lo, hi + 1, dtype=jnp.int32
    )
    for _ in range(ctrs.ndim):
        f = jax.vmap(f)
    return f(keys, ctrs)


def draw_uniform_counters(
    base: jax.Array, kind: int, g: int, n: int, ctrs, lo: int, hi: int
) -> jax.Array:
    """Vectorized draws for one (group, node) over an array of counters; element [k]
    equals draw_uniform(base, kind, g, n, ctrs[k], lo, hi) exactly. Used by the oracle's
    predraw tables — same derivation as the kernel's per-tick draws."""
    return jax.vmap(lambda c: draw_uniform(base, kind, g, n, c, lo, hi))(ctrs)


def edge_ok_mask(base: jax.Array, tick, shape: tuple, p_drop: float) -> jax.Array:
    """(G, N, N) boolean mask for tick `tick`: element [g, s-1, r-1] is True iff the
    directed message s -> r in group g survives this tick. One shaped draw per tick,
    shared verbatim by oracle and kernel (SEMANTICS.md §4)."""
    if p_drop <= 0.0:
        return jnp.ones(shape, dtype=bool)
    k = jax.random.fold_in(jax.random.fold_in(base, KIND_FAULT), tick)
    return ~jax.random.bernoulli(k, p_drop, shape)


def delay_mask(base: jax.Array, tick, shape: tuple, lo: int, hi: int) -> jax.Array:
    """(G, N, N) int32 of per-directed-pair message delays for sends at tick `tick`,
    uniform on [lo, hi] inclusive (SEMANTICS.md §10). Element [g, s-1, r-1] is the
    delay of the exchange s sends to r this tick. One shaped draw per tick, shared
    verbatim by oracle, kernel, and native engine — same pattern as edge_ok_mask."""
    if lo == hi:
        return jnp.full(shape, lo, dtype=jnp.int32)
    k = jax.random.fold_in(jax.random.fold_in(base, KIND_DELAY), tick)
    return jax.random.randint(k, shape, lo, hi + 1, dtype=jnp.int32)


def event_mask(base: jax.Array, kind: int, tick, shape: tuple, p: float) -> jax.Array:
    """Shaped boolean event draw for tick `tick` (True = event fires). One draw per
    (kind, tick), shared verbatim by oracle and kernel — the fault-event analogue of
    `edge_ok_mask` (SEMANTICS.md §9: crash/restart/link-fail/link-heal events)."""
    if p <= 0.0:
        return jnp.zeros(shape, dtype=bool)
    k = jax.random.fold_in(jax.random.fold_in(base, kind), tick)
    return jax.random.bernoulli(k, p, shape)
