"""Canonical randomness derivation — the single source of truth for every random draw.

The reference draws from JVM global RNGs (java.util.Random in Commons.kt:33-34, timer
jitter Commons.kt:23, backoff RaftServer.kt:221), which is irreproducible. Here every
draw is a counted threefry evaluation keyed by (kind, group, node, per-node counter), so
the scalar CPU oracle and the vectorized TPU kernel — and any backend, any device —
see bit-identical values. See SEMANTICS.md §4.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

jax.config.update("jax_threefry_partitionable", True)

KIND_TIMEOUT = 0
KIND_BACKOFF = 1
KIND_FAULT = 2


def base_key(seed: int) -> jax.Array:
    return jax.random.key(seed)


def _key(base: jax.Array, kind, g, n, ctr) -> jax.Array:
    k = jax.random.fold_in(base, kind)
    k = jax.random.fold_in(k, g)
    k = jax.random.fold_in(k, n)
    k = jax.random.fold_in(k, ctr)
    return k


def draw_uniform(base: jax.Array, kind, g, n, ctr, lo: int, hi: int) -> jax.Array:
    """One scalar draw, uniform on the inclusive range [lo, hi].

    Inclusivity matches Kotlin's `(a..b).random()` (reference Commons.kt:33-34).
    """
    return jax.random.randint(_key(base, kind, g, n, ctr), (), lo, hi + 1, dtype=jnp.int32)


def draw_uniform_grid(
    base: jax.Array, kind: int, ctrs: jax.Array, lo: int, hi: int
) -> jax.Array:
    """Vectorized draws over a (G, N) counter grid; element [g, i] equals
    draw_uniform(base, kind, g, n=i+1, ctrs[g, i], lo, hi) exactly."""
    G, N = ctrs.shape
    g_idx = jnp.arange(G, dtype=jnp.int32)[:, None].repeat(N, axis=1)
    n_idx = jnp.arange(1, N + 1, dtype=jnp.int32)[None, :].repeat(G, axis=0)
    f = lambda g, n, c: draw_uniform(base, kind, g, n, c, lo, hi)
    return jax.vmap(jax.vmap(f))(g_idx, n_idx, ctrs)


def draw_uniform_counters(
    base: jax.Array, kind: int, g: int, n: int, ctrs, lo: int, hi: int
) -> jax.Array:
    """Vectorized draws for one (group, node) over an array of counters; element [k]
    equals draw_uniform(base, kind, g, n, ctrs[k], lo, hi) exactly. Used by the oracle's
    predraw tables — same derivation as the kernel's per-tick draws."""
    return jax.vmap(lambda c: draw_uniform(base, kind, g, n, c, lo, hi))(ctrs)


def edge_ok_mask(base: jax.Array, tick, shape: tuple, p_drop: float) -> jax.Array:
    """(G, N, N) boolean mask for tick `tick`: element [g, s-1, r-1] is True iff the
    directed message s -> r in group g survives this tick. One shaped draw per tick,
    shared verbatim by oracle and kernel (SEMANTICS.md §4)."""
    if p_drop <= 0.0:
        return jnp.ones(shape, dtype=bool)
    k = jax.random.fold_in(jax.random.fold_in(base, KIND_FAULT), tick)
    return ~jax.random.bernoulli(k, p_drop, shape)
