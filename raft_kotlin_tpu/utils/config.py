"""Simulation configuration.

The reference hard-codes every pacing constant (see BASELINE.md); here they are the
defaults of a frozen dataclass, expressed in simulation ticks (1 tick = 100 ms of
reference wall-time). Sources: election timeout 20_000..23_000 ms
(reference Commons.kt:23), heartbeat period 2_000 ms (RaftServer.kt:115), vote-round
window 25 s (RaftServer.kt:189,214), vote retry 5_000 ms (Commons.kt:37), candidate
backoff 2_000..3_000 ms (RaftServer.kt:221).
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class RaftConfig:
    """Static configuration for one simulation (shared by oracle and TPU kernel)."""

    n_groups: int = 1
    n_nodes: int = 3
    log_capacity: int = 64

    # Pacing, in ticks. Inclusive uniform ranges match Kotlin's (a..b).random().
    el_lo: int = 200          # election timeout lower bound
    el_hi: int = 230          # election timeout upper bound (inclusive)
    hb_ticks: int = 20        # heartbeat / replication period
    round_ticks: int = 250    # vote-round window (the 25 s latch)
    retry_ticks: int = 50     # vote RPC retry period within a round
    bo_lo: int = 20           # candidate backoff lower bound
    bo_hi: int = 30           # candidate backoff upper bound (inclusive)

    # Workload: every cmd_period ticks (if > 0), inject command value = tick index
    # into node cmd_node of every group (reference: GET /cmd/{command} on any node,
    # RaftServer.kt:87-90 — no leader check).
    cmd_period: int = 0
    cmd_node: int = 1

    # Fault injection (SEMANTICS.md §§4, 9). p_drop: per-tick iid drop probability per
    # directed edge. p_crash/p_restart: per-tick process crash / rejoin probability per
    # node (restart wipes all node state — reference quirk l, RaftServer.kt:35-48).
    # p_link_fail/p_link_heal: per-tick transition probabilities of the persistent
    # directed-link health mask (partitions).
    p_drop: float = 0.0
    p_crash: float = 0.0
    p_restart: float = 0.0
    p_link_fail: float = 0.0
    p_link_heal: float = 0.0

    seed: int = 0

    @property
    def majority(self) -> int:
        # RaftServer.kt:44
        return self.n_nodes // 2 + 1

    def stressed(self, factor: int = 10) -> "RaftConfig":
        """A time-compressed variant: all pacing constants divided by `factor`.

        Preserves the reference's ratios (timeout : heartbeat : backoff) while packing
        `factor`x more protocol activity into each wall-clock second of simulation —
        used by election-churn benchmarks.
        """
        return dataclasses.replace(
            self,
            el_lo=max(1, self.el_lo // factor),
            el_hi=max(1, self.el_hi // factor),
            hb_ticks=max(1, self.hb_ticks // factor),
            round_ticks=max(1, self.round_ticks // factor),
            retry_ticks=max(1, self.retry_ticks // factor),
            bo_lo=max(1, self.bo_lo // factor),
            bo_hi=max(1, self.bo_hi // factor),
        )
