"""Simulation configuration.

The reference hard-codes every pacing constant (see BASELINE.md); here they are the
defaults of a frozen dataclass, expressed in simulation ticks (1 tick = 100 ms of
reference wall-time). Sources: election timeout 20_000..23_000 ms
(reference Commons.kt:23), heartbeat period 2_000 ms (RaftServer.kt:115), vote-round
window 25 s (RaftServer.kt:189,214), vote retry 5_000 ms (Commons.kt:37), candidate
backoff 2_000..3_000 ms (RaftServer.kt:221).
"""

from __future__ import annotations

import dataclasses
from typing import Optional


# Canonical partition-program kind codes (utils/rng.scenario_link_down —
# shared verbatim by kernel aux assembly, Python oracle and native engine).
PART_NONE, PART_SPLIT, PART_ASYM, PART_LEADER = 0, 1, 2, 3
PART_KINDS = ("split", "asym", "leader")


@dataclasses.dataclass(frozen=True)
class ScenarioSpec:
    """Per-group scenario heterogeneity (the fuzzing-farm bank, SEMANTICS.md
    §12). When `RaftConfig.scenario` is set, `ops/tick.make_rng` samples a
    ScenarioBank — per-group fault thresholds, delay windows and partition
    programs — from a counted threefry stream keyed by
    (farm_seed, universe_id = universe_base + group), so every group is a
    distinct, reproducible universe and the bank rides the rng operand
    (seed- and universe-independent compilation). The spec itself is static
    and hashable: it is part of the config, so a replay artifact is just
    the config dict.

    Channels (each sampled per group, uniform over its integer domain):
    - drop/crash/restart/link_fail/link_heal: per-group 23-bit uint32
      probability thresholds on [0, p_threshold(<ch>_max)] (utils/rng —
      integer-exact across oracle and kernels; <ch>_max = 0 disables).
    - delay_windows: per-group [lo, hi] delay windows sampled WITHIN the
      run's mailbox window [delay_lo, delay_hi] (requires delay_lo <
      delay_hi; the run's regime — known-delivery etc. — is preserved).
    - partitions: the enabled scripted partition-program kinds, a subset
      of PART_KINDS; each group draws one program (or none) with
      flapping window (period, duty, phase) — see utils/rng.
      "leader" programs read the PRE-TICK roles, so they are unavailable
      to engines whose aux is precomputed ahead of state (the fused-T
      Pallas kernel falls back to T=1; everything else works).

    `warmup_down` (§15, SEMANTICS.md) is NOT a sampled channel but a
    deterministic schedule post-processed onto the crash/restart masks
    (utils/rng.apply_warmup_faults — no draws consumed): every non-cmd
    node is held crashed for t < warmup_down and rejoins at t ==
    warmup_down, so cmd_node wins every group's first election and a
    compaction universe stays capacity-clean at any group count.

    `degenerate=True` is the provable degenerate case: the bank is built
    from the config's own SCALAR fault fields (all groups identical), and
    every engine must be bit-identical to the scalar path — the farm's
    correctness anchor (tests/test_fuzz.py)."""

    farm_seed: int = 0
    universe_base: int = 0
    degenerate: bool = False
    drop_max: float = 0.0
    crash_max: float = 0.0
    restart_max: float = 0.0
    link_fail_max: float = 0.0
    link_heal_max: float = 0.0
    delay_windows: bool = False
    partitions: tuple = ()
    part_period_lo: int = 8
    part_period_hi: int = 64
    # §15 warmup-down (SEMANTICS.md §15): for warmup_down = W > 0, every
    # node except cfg.cmd_node is held crashed on ticks t < W (crash
    # asserted, random restarts suppressed) and restarted at exactly
    # t == W. Deterministic — no draws consumed — so all engines apply
    # the identical rule (utils/rng.apply_warmup_faults). Because quirk k
    # routes every client command to cmd_node, this makes cmd_node win
    # each group's first election by term + log dominance: the one
    # universe family whose committed prefix keeps pace with the client
    # in EVERY group, which a bounded §15 ring needs to stay
    # capacity-clean at any group count.
    warmup_down: int = 0
    # §19 continuous-scheduler channels (SEMANTICS.md §19):
    # - timeout_windows: sample a per-group election-timeout window
    #   [el_lo, el_hi] nested inside the config's window (the §9.3 timing
    #   observatory's spread channel). Engines that bake scalar el bounds
    #   (Pallas, oracle, native) refuse such banks loudly.
    # - life_lo/life_hi: per-group lifetime in ticks — the horizon-reached
    #   arm of the retirement predicate (life_hi = 0 disables).
    # - quiesce_ticks: retire a group after this many consecutive calm
    #   ticks (live leader, no election activity, no fault transitions);
    #   0 disables. Static (not sampled): part of the retire predicate
    #   compiled into the monitor carry, not a bank channel.
    timeout_windows: bool = False
    life_lo: int = 0
    life_hi: int = 0
    quiesce_ticks: int = 0
    # §20 client-stream channels (SEMANTICS.md §20): the serving path's
    # device-resident load generator samples per-group workload shape —
    # write rate, read rate, and key skew — as bank rows, evaluated via
    # the §17 kernel-twin draws (bit-identical in-scan and host-eager;
    # the device-generator ≡ host-queue equality theorem rides on it).
    # - client_rate_max: per-group writes/tick drawn uniform in
    #   [1, client_rate_max] (0 disables the channel; the run then uses
    #   the classical cmd_period workload).
    # - client_read_max: per-group reads/tick drawn uniform in
    #   [1, client_read_max] (0 disables; cfg.read_batch applies).
    # - client_hot_max: per-group hot-key weight in permille, drawn
    #   uniform in [0, client_hot_max] — the drawn fraction of reads and
    #   writes lands on slot 0, the rest uniform over the KV slots.
    client_rate_max: int = 0
    client_read_max: int = 0
    client_hot_max: int = 0

    def __post_init__(self):
        # Coerce to tuple so a list argument cannot build an unhashable
        # "frozen" spec (lru_cache keys on the whole config downstream).
        object.__setattr__(self, "partitions", tuple(self.partitions))
        for ch in ("drop", "crash", "restart", "link_fail", "link_heal"):
            p = getattr(self, f"{ch}_max")
            if not (0.0 <= p <= 1.0):
                raise ValueError(f"{ch}_max must be in [0, 1], got {p}")
        bad = [k for k in self.partitions if k not in PART_KINDS]
        if bad:
            raise ValueError(f"unknown partition kinds {bad}; "
                             f"valid: {PART_KINDS}")
        if not (1 <= self.part_period_lo <= self.part_period_hi):
            raise ValueError(
                f"need 1 <= part_period_lo <= part_period_hi, got "
                f"{self.part_period_lo}/{self.part_period_hi}")
        if self.warmup_down < 0:
            raise ValueError(
                f"warmup_down must be >= 0, got {self.warmup_down}")
        if self.warmup_down > 0 and self.degenerate:
            raise ValueError(
                "warmup_down is a scheduled fault program — it cannot ride "
                "a degenerate (scalar-anchor) spec")
        if not (0 <= self.life_lo <= self.life_hi):
            raise ValueError(
                f"need 0 <= life_lo <= life_hi, got "
                f"{self.life_lo}/{self.life_hi}")
        if self.life_hi > 0 and self.life_lo < 1:
            raise ValueError("life_lo must be >= 1 when lifetimes are on")
        if self.quiesce_ticks < 0:
            raise ValueError(
                f"quiesce_ticks must be >= 0, got {self.quiesce_ticks}")
        if self.degenerate and (self.timeout_windows or self.life_hi > 0):
            raise ValueError(
                "timeout_windows/lifetimes are sampled channels — they "
                "cannot ride a degenerate (scalar-anchor) spec")
        for ch in ("client_rate_max", "client_read_max", "client_hot_max"):
            if getattr(self, ch) < 0:
                raise ValueError(f"{ch} must be >= 0, got {getattr(self, ch)}")
        if self.client_hot_max > 1000:
            raise ValueError(
                f"client_hot_max is permille, must be <= 1000, got "
                f"{self.client_hot_max}")
        if self.degenerate and self.has_clients:
            raise ValueError(
                "client-stream channels are sampled — they cannot ride a "
                "degenerate (scalar-anchor) spec")

    @property
    def has_faults(self) -> bool:
        """Whether the sampled bank carries crash/restart channels or the
        §15 warmup-down schedule (the phase-F faults flag must compile
        in)."""
        return self.warmup_down > 0 or (not self.degenerate and (
            self.crash_max > 0 or self.restart_max > 0))

    @property
    def has_links(self) -> bool:
        """Whether the sampled bank carries link fail/heal channels (the
        phase-F link-transition flag must compile in)."""
        return not self.degenerate and (
            self.link_fail_max > 0 or self.link_heal_max > 0)

    @property
    def needs_state(self) -> bool:
        """Whether per-tick aux assembly must read pre-tick STATE (leader
        isolation) — engines that precompute aux ahead of state (the fused
        Pallas kernel) cannot run such banks and fall back."""
        return (not self.degenerate) and ("leader" in self.partitions)

    @property
    def has_clients(self) -> bool:
        """Whether the bank carries §20 client-stream channels (the
        serving path's device-resident load generator)."""
        return (self.client_rate_max > 0 or self.client_read_max > 0
                or self.client_hot_max > 0)


def config_from_dict(d: dict) -> "RaftConfig":
    """Rebuild a RaftConfig from dataclasses.asdict output (the triage /
    fuzz-corpus replay path): the nested scenario dict becomes a
    ScenarioSpec again and JSON-roundtripped lists re-tuple."""
    d = dict(d)
    scen = d.get("scenario")
    if isinstance(scen, dict):
        scen = dict(scen)
        if "partitions" in scen:
            scen["partitions"] = tuple(scen["partitions"])
        d["scenario"] = ScenarioSpec(**scen)
    return RaftConfig(**d)


@dataclasses.dataclass(frozen=True)
class RaftConfig:
    """Static configuration for one simulation (shared by oracle and TPU kernel)."""

    n_groups: int = 1
    n_nodes: int = 3
    log_capacity: int = 64

    # Storage dtype of the log arrays (log_term/log_cmd): "int32" (default) or
    # "int16" — the deep-log lever (BASELINE config 5: 100k groups x 7 nodes x
    # 10k-entry logs = 28 GB of int32 terms; int16 halves it, SURVEY.md:350-352).
    # All arithmetic stays int32: values widen at read, narrow at write —
    # VALUES ARE NOT RANGE-CHECKED; writes outside int16 silently wrap. int16
    # is for bounded headless sweeps where both stored quantities fit:
    # terms < 32768 (terms grow ~1 per election round; at reference-ratio
    # pacing that is >700k ticks, but a degenerate churn config gets there in
    # ~65k) and commands < 32768 (the cmd_period workload stores the tick
    # index, so runs must stay under 32768 ticks). The Simulator API accepts
    # int16 with a BOUNDED vocabulary: interned ids live in [1<<14, 2^15)
    # (api/simulator.INTERN_BASE16, capacity-checked), which additionally
    # bounds cmd_period runs to < 16384 ticks for unambiguous de-interning.
    log_dtype: str = "int32"

    # Pacing, in ticks. Inclusive uniform ranges match Kotlin's (a..b).random().
    el_lo: int = 200          # election timeout lower bound
    el_hi: int = 230          # election timeout upper bound (inclusive)
    hb_ticks: int = 20        # heartbeat / replication period
    round_ticks: int = 250    # vote-round window (the 25 s latch)
    retry_ticks: int = 50     # vote RPC retry period within a round
    bo_lo: int = 20           # candidate backoff lower bound
    bo_hi: int = 30           # candidate backoff upper bound (inclusive)

    # Workload: every cmd_period ticks (if > 0), inject command value = tick index
    # into node cmd_node of every group (reference: GET /cmd/{command} on any node,
    # RaftServer.kt:87-90 — no leader check).
    cmd_period: int = 0
    cmd_node: int = 1

    # Fault injection (SEMANTICS.md §§4, 9). p_drop: per-tick iid drop probability per
    # directed edge. p_crash/p_restart: per-tick process crash / rejoin probability per
    # node (restart wipes all node state — reference quirk l, RaftServer.kt:35-48).
    # p_link_fail/p_link_heal: per-tick transition probabilities of the persistent
    # directed-link health mask (partitions).
    p_drop: float = 0.0
    p_crash: float = 0.0
    p_restart: float = 0.0
    p_link_fail: float = 0.0
    p_link_heal: float = 0.0

    # Message latency (SEMANTICS.md §10): per-exchange request delay drawn uniform
    # [delay_lo, delay_hi] ticks inclusive (per directed pair per send tick). 0/0 =
    # synchronous-within-tick exchanges (§1 [canon], the default — reference RPCs
    # are ms-scale against 100 ms ticks). `mailbox=True` forces the mailbox
    # implementation even at delay 0/0 (bit-identical to the synchronous path —
    # the τ=0 degeneracy differential tests rely on it).
    delay_lo: int = 0
    delay_hi: int = 0
    mailbox: bool = False

    # §15 log compaction / snapshotting (Raft §7; SEMANTICS.md §15).
    # compact_watermark W > 0 enables the subsystem: each tick (phase C),
    # every live node whose unfolded committed backlog commit - snap_index
    # reaches W folds up to compact_chunk oldest committed entries into
    # its fixed-shape snapshot (snap_index/snap_term/snap_digest) and
    # slides the ring window (ring base == snap_index). W = 0 (default)
    # compiles the subsystem OUT — the pre-§15 program, bit-identical
    # (the migration-equality contract, tests/test_compaction.py).
    compact_watermark: int = 0
    compact_chunk: int = 8

    # §16 physical ring window (ISSUE 14). ring_capacity C_phys < C
    # decouples log STORAGE from logical capacity: under compaction the
    # log arrays (and every position-indexed plane the engines derive
    # from them) allocate (N, C_phys, G) while logical positions stay
    # unbounded i32 and the §15 translate-or-latch map goes mod C_phys.
    # Requires compact_watermark > 0 (without folds nothing reclaims
    # ring rows) and C_phys >= watermark + chunk (the fold must always
    # have room to make progress before the window fills). The existing
    # cap_ov latch is the loud-fail when a group's backlog outruns the
    # physical window. None (default) keeps the physical window ==
    # log_capacity — the bit-identical r15 program.
    ring_capacity: Optional[int] = None

    # §20 serving path (SEMANTICS.md §20). serve_slots S > 0 enables the
    # applied KV state machine: a fixed-slot (S, G) store folded from the
    # committed prefix as an end-of-tick apply phase (slot = cmd mod S),
    # advanced as a carry-resident observer in every engine — bit-neutral
    # to the protocol state, exactly like the recorder/monitor. S = 0
    # (default) compiles the subsystem OUT: the pre-§20 program,
    # bit-identical (the migration-equality contract every dimension
    # follows).
    serve_slots: int = 0
    # Apply-phase budget: at most apply_chunk committed entries fold into
    # the KV store per group per tick (fixed iteration count — the same
    # bounded-progress shape as §15 compact_chunk).
    apply_chunk: int = 4
    # Log-free linearizable reads (Raft §6.4 / §8): read_batch reads per
    # group per tick when no bank read channel overrides it; read_path
    # picks the confirmation rule — "readindex" (commit-frontier
    # confirmation, served at a live leader: +2 ticks submit→serve) or
    # "lease" (heartbeat-lease read at an armed leader: +1 tick). The
    # read path is a routed plan dimension (parallel/autotune.py).
    read_batch: int = 0
    read_path: str = "readindex"

    # §21 streaming ops plane (SEMANTICS.md §21). series_windows W > 0
    # enables the carry-resident multi-channel TIME-SERIES ring: a fixed
    # (W, K) int32 block in the monitor carry sampled every series_stride
    # ticks (0 = auto: the stride tiles the run exactly like the history
    # ring), one column per telemetry.SERIES_CHANNELS entry. event_capacity
    # E > 0 enables the bounded EVENT ring: the first E encoded
    # (kind, tick, group, arg) events of the run, with a loud
    # events_dropped counter once full. Both are pre/post-tick state
    # reductions riding the monitor carry — bit-neutral and engine-
    # independent by the same contract as the recorder/monitor, and 0
    # (default) compiles them OUT: the pre-§21 carry, bit-identical.
    series_windows: int = 0
    series_stride: int = 0
    event_capacity: int = 0

    seed: int = 0

    # Per-group scenario heterogeneity (the fuzzing-farm bank, SEMANTICS.md
    # §12): None = the classical single-universe run. When set, make_rng
    # samples the per-group ScenarioBank and threads it through every
    # engine's rng operand; the scalar fault fields above still apply as
    # baselines for any channel the spec does not sample.
    scenario: Optional[ScenarioSpec] = None

    def __post_init__(self):
        if not (0 <= self.delay_lo <= self.delay_hi):
            raise ValueError(
                f"need 0 <= delay_lo <= delay_hi, got {self.delay_lo}/{self.delay_hi}")
        if self.log_dtype not in ("int32", "int16"):
            raise ValueError(f"log_dtype must be int32 or int16, got {self.log_dtype}")
        if self.compact_watermark < 0:
            raise ValueError(
                f"compact_watermark must be >= 0, got {self.compact_watermark}")
        if self.compact_watermark > 0:
            if self.compact_chunk < 1:
                raise ValueError(
                    f"compact_chunk must be >= 1, got {self.compact_chunk}")
            if self.compact_watermark > self.log_capacity:
                raise ValueError(
                    "compact_watermark must be <= log_capacity (a window "
                    "that can never fold cannot bound the log)")
        if self.ring_capacity is not None:
            if self.compact_watermark <= 0:
                raise ValueError(
                    "ring_capacity needs compact_watermark > 0 — without "
                    "folds nothing ever reclaims physical ring rows")
            if self.ring_capacity < self.compact_watermark + self.compact_chunk:
                raise ValueError(
                    f"ring_capacity {self.ring_capacity} must be >= "
                    f"compact_watermark + compact_chunk "
                    f"({self.compact_watermark} + {self.compact_chunk}): the "
                    "fold must fit the window it is reclaiming")
            if self.ring_capacity > self.log_capacity:
                raise ValueError(
                    f"ring_capacity {self.ring_capacity} must be <= "
                    f"log_capacity {self.log_capacity} (the physical window "
                    "bounds storage, never extends it)")
        if self.serve_slots < 0:
            raise ValueError(
                f"serve_slots must be >= 0, got {self.serve_slots}")
        if self.serve_slots > 0:
            if self.apply_chunk < 1:
                raise ValueError(
                    f"apply_chunk must be >= 1, got {self.apply_chunk}")
            if self.read_batch < 0:
                raise ValueError(
                    f"read_batch must be >= 0, got {self.read_batch}")
            if self.read_path not in ("readindex", "lease"):
                raise ValueError(
                    f"read_path must be readindex or lease, got "
                    f"{self.read_path!r}")
        if self.series_windows < 0 or self.event_capacity < 0:
            raise ValueError(
                f"series_windows/event_capacity must be >= 0, got "
                f"{self.series_windows}/{self.event_capacity}")
        if self.series_stride < 0:
            raise ValueError(
                f"series_stride must be >= 0, got {self.series_stride}")
        if self.series_stride > 0 and self.series_windows <= 0:
            raise ValueError(
                "series_stride needs series_windows > 0 — a stride "
                "without a ring samples into nothing")
        s = self.scenario
        if s is not None and s.has_clients and self.serve_slots <= 0:
            raise ValueError(
                "client-stream channels need serve_slots > 0 — the "
                "generated commands must have an applied store to land in")
        if s is not None and not s.degenerate:
            if s.delay_windows and not self.delay_lo < self.delay_hi:
                raise ValueError(
                    "scenario.delay_windows needs a real run window "
                    f"(delay_lo < delay_hi), got {self.delay_lo}/{self.delay_hi}")
            if s.partitions and self.n_nodes < 2:
                raise ValueError("partition programs need n_nodes >= 2")
            if s.timeout_windows and not self.el_lo < self.el_hi:
                raise ValueError(
                    "scenario.timeout_windows needs a real election window "
                    f"(el_lo < el_hi), got {self.el_lo}/{self.el_hi}")

    @property
    def uses_mailbox(self) -> bool:
        """Whether exchanges route through the deliverable-at-tick mailbox
        (SEMANTICS.md §10) instead of resolving synchronously within the tick."""
        return self.mailbox or self.delay_hi > 0

    @property
    def uses_compaction(self) -> bool:
        """Whether the §15 snapshot/compaction subsystem is compiled in:
        snapshot state present, ring-window log addressing, InstallSnapshot
        exchanges, the end-of-tick fold phase. False (W = 0) compiles the
        bit-identical pre-§15 program — THE migration-equality switch."""
        return self.compact_watermark > 0

    @property
    def uses_serving(self) -> bool:
        """Whether the §20 serving path is compiled in: the applied KV
        store, the read path, the client-latency histograms, and (when the
        bank carries client channels) the device-resident load generator.
        False (S = 0) compiles the bit-identical pre-§20 program."""
        return self.serve_slots > 0

    @property
    def uses_ops_plane(self) -> bool:
        """Whether the §21 streaming ops plane rides the monitor carry:
        the multi-channel series ring and/or the bounded event ring.
        False (both 0) compiles the bit-identical pre-§21 carry."""
        return self.series_windows > 0 or self.event_capacity > 0

    @property
    def known_delivery(self) -> bool:
        """Whether every §10 delivery is fully determined at tick start:
        delay_lo >= 1 forbids same-tick send-and-deliver, so each tick's
        delivery set comes entirely from slots filled on EARLIER ticks.
        This is the regime where the batched/frontier-cache deep engines
        run under the mailbox (ops/tick.py BodyFlags.batched, r7); τ=0
        mailbox configs keep the per-pair engine."""
        return self.uses_mailbox and self.delay_lo >= 1

    @property
    def phys_capacity(self) -> int:
        """Physical rows per (node, group) log plane — the allocation and
        ring-translate modulus every engine uses (§16). ring_capacity when
        set, else log_capacity: logical positions are bounded by
        log_capacity without compaction, by nothing (i32) with it."""
        return (self.ring_capacity if self.ring_capacity is not None
                else self.log_capacity)

    @property
    def uses_dyn_log(self) -> bool:
        """Whether the kernel uses dynamic (gather/scatter) log addressing —
        the deep-log band. THE one threshold shared by engine selection
        (ops/tick.make_aux), backend choice (ops/pallas_tick.choose_impl),
        and sharded-run routing (parallel/mesh.make_sharded_run). Keyed on
        the PHYSICAL window (§16): a deep logical capacity bounded to a
        small ring addresses few enough resident rows for the shallow
        band's columnar one-hot forms — the ring's perf lever."""
        return self.phys_capacity >= 256

    @property
    def majority(self) -> int:
        # RaftServer.kt:44
        return self.n_nodes // 2 + 1

    # -- HBM budget (BASELINE config 5 planning; SURVEY.md:350-352) -----------

    def state_bytes_per_group(self) -> int:
        """Bytes of RaftState per group under this config (log dtype included).
        The log dominates for deep-log configs: N * C_phys * 2 arrays —
        physical rows, so a §16 ring window shrinks the byte model by
        ~C / C_phys."""
        N, C = self.n_nodes, self.phys_capacity
        itemsize = 2 if self.log_dtype == "int16" else 4
        log = N * C * 2 * itemsize
        per_node_i32 = 17 * N * 4     # (N,) int32 grids incl. counters/timers
        per_node_b = 3 * N * 1        # el_armed/hb_armed/up as packed bool
        pair = 3 * N * N * 4 + N * N  # responded/next/match (+link_up bool)
        mail = 13 * N * N * 4 if self.uses_mailbox else 0
        return log + per_node_i32 + per_node_b + pair + mail

    def hbm_bytes(self, working_factor: float = 2.0) -> int:
        """Estimated device-memory footprint of a run: state x working_factor
        (XLA holds input + output copies of the state across a tick; donation
        reduces but rarely eliminates the second copy) plus per-tick aux masks."""
        aux = self.n_groups * (self.n_nodes ** 2) * 5  # masks, generously
        return int(self.n_groups * self.state_bytes_per_group() * working_factor + aux)

    def max_groups_for_hbm(self, hbm_bytes: int = 14 * 10**9,
                           working_factor: float = 2.0) -> int:
        """Largest n_groups fitting `hbm_bytes` (default: one 16 GB chip with 2 GB
        headroom) under this config's per-group cost — the groups-per-chip
        ceiling for BASELINE config-5 planning."""
        per = self.state_bytes_per_group() * working_factor + self.n_nodes ** 2 * 5
        return int(hbm_bytes // per)

    def stressed(self, factor: int = 10) -> "RaftConfig":
        """A time-compressed variant: all pacing constants divided by `factor`.

        Preserves the reference's ratios (timeout : heartbeat : backoff) while packing
        `factor`x more protocol activity into each wall-clock second of simulation —
        used by election-churn benchmarks.
        """
        return dataclasses.replace(
            self,
            el_lo=max(1, self.el_lo // factor),
            el_hi=max(1, self.el_hi // factor),
            hb_ticks=max(1, self.hb_ticks // factor),
            round_ticks=max(1, self.round_ticks // factor),
            retry_ticks=max(1, self.retry_ticks // factor),
            bo_lo=max(1, self.bo_lo // factor),
            bo_hi=max(1, self.bo_hi // factor),
        )
