"""Scan-carry flight recorder + phase/engine profiler scopes (ISSUE 5).

The host-side observability path (utils/metrics.MetricsRecorder over
make_instrumented_run) is a per-window JSONL stream — right for dashboards,
wrong inside a production `jit`/`scan`/`shard_map` soak at 100k groups,
where per-tick (n_ticks,)-shaped metric outputs grow the scan's stacked
output arrays and anything host-visible forces a device sync. This module
is the ON-DEVICE recorder: a small fixed-shape pytree of scalar int32
counters accumulated INSIDE the tick/scan carry — per-tick health costs a
handful of fused (N, G)-wide reductions and is read back ONCE per run.

Every engine threads the same recorder through its carry:
- the XLA tick scan        (ops/tick.make_run(telemetry=True)),
- the Pallas flat-carry    (ops/pallas_tick.make_pallas_scan(telemetry=True)),
- the deep-log fc/batched  (ops/deep_cache.make_deep_scan /
                            make_sharded_deep_scan(telemetry=True)),
- the sharded runner       (parallel/mesh.make_sharded_run(telemetry=True)).

BIT-NEUTRALITY CONTRACT: the recorder reads ONLY the pre/post-tick states
the engines already produce — ops/tick.phase_body is never touched, so the
protocol bits are identical with the recorder on or off on every engine
(tests/test_telemetry.py pins this differentially across the sync,
mailbox, deep-log, int16, Pallas and sharded suites). For the Pallas
megakernel the accumulation runs on the flat scan carry BETWEEN kernel
launches (plain XLA reductions the fusion compiler folds), not inside
Mosaic: per-tile in-kernel partials would add output blocks and i1
reductions to a kernel whose bit-exactness is the project's core contract,
for no additional information — the flat carry already holds the same
post-tick values the tile wrote.

Counter semantics (all () int32, cumulative over the run; derived from
state TRANSITIONS, so they are engine-independent by construction):

- elections_started   sum of per-node `rounds` deltas (the ONE canonical
                      elections definition, shared with utils.metrics and
                      parallel.mesh).
- leader_changes      nodes that newly became LIVE leaders this tick
                      (role -> LEADER with up; a crashed leader's inert
                      role does not count).
- votes_granted       vote grants tallied this tick: positive `votes`
                      movement against a baseline of 0 for nodes that
                      started a round or restarted this tick (both reset
                      the tally before re-counting).
- commit_advances     sum of positive per-node commit deltas (quirk e can
                      legitimately LOWER a stale follower's commit; those
                      are not advances).
- append_accepts      match-frontier advance units: positive match_index
                      movement over pairs whose owner neither won an
                      election nor restarted this tick (both wipe the pair
                      row to 0 — bookkeeping, not replication).
- append_rejects      next_index decrements over the same owner mask (a
                      §6.2 reject walks next_index back exactly 1; the
                      quirk-b win jump and restart wipes are masked out).
- mailbox_inflight_hw high-water of the §10 in-flight slot count (vq/aq
                      slots with due >= 0, summed over pairs and groups);
                      0 on non-mailbox configs.
- ov_fallbacks        deep-engine frontier-cache overflow events: ticks
                      whose OV flag fired (the runner re-ran those bits on
                      the plain engine — time lost, never bits). 0 on
                      engines that carry no cache.
- fault_events        §9 liveness transitions (crashes + restarts).

Profiler scopes: PhaseScopes wraps ops/tick.phase_body's lattice in
`jax.named_scope` regions named exactly after the per-phase chain-depth
attribution keys (`opcount.phase_body_chain_depth(by_phase=True)`:
"F0", "p1" ... "p5", under the "raft/" prefix) so a Perfetto/TensorBoard
trace's op groups line up with the chain-depth model; `engine_scope` tags
each engine's tick, and `trace_span` is the host-side
jax.profiler.TraceAnnotation for run-level regions (scripts/
probe_telemetry.py). All three are trace-time metadata only — they name
HLO ops, they never add one.
"""

from __future__ import annotations

import contextlib
from typing import Dict, Optional

import jax
import jax.numpy as jnp

from raft_kotlin_tpu.constants import LEADER

_I32 = jnp.int32

# Canonical counter order (the recorder pytree's field set).
TELEMETRY_FIELDS = (
    "elections_started",
    "leader_changes",
    "votes_granted",
    "commit_advances",
    "append_accepts",
    "append_rejects",
    "mailbox_inflight_hw",
    "ov_fallbacks",
    "fault_events",
)

# The state fields one telemetry step reads (node grids (N, G) + pair grids
# (N, N, G) + optional §10 due slots) — the flat-carry runners build their
# views from exactly this list.
TELEMETRY_STATE_FIELDS = (
    "role", "up", "rounds", "votes", "commit", "match_index", "next_index",
)
TELEMETRY_MAILBOX_FIELDS = ("vq_due", "aq_due")


def telemetry_zeros() -> Dict[str, jax.Array]:
    """A fresh recorder: every counter a () int32 zero."""
    return {k: jnp.zeros((), _I32) for k in TELEMETRY_FIELDS}


def _s(x) -> jax.Array:
    """Whole-array count/sum to a () int32 (bool or int input)."""
    return jnp.sum(x.astype(_I32))


def telemetry_step_arrays(prev: dict, cur: dict, tel: Dict[str, jax.Array],
                          ov: Optional[jax.Array] = None
                          ) -> Dict[str, jax.Array]:
    """One recorder step from pre/post-tick state VIEWS.

    `prev`/`cur` map TELEMETRY_STATE_FIELDS (plus TELEMETRY_MAILBOX_FIELDS
    when the config runs the §10 mailbox) to arrays in canonical RaftState
    shapes: node grids (N, G), pair grids (N, N, G), groups-minor. Bool
    fields may arrive as int stand-ins (the Pallas flat carry) — liveness
    is read as `!= 0`. `ov` is an optional () scalar (bool/int) counting a
    deep-engine cache-overflow event this tick. Returns the advanced
    recorder (a new dict; inputs untouched)."""
    prev_up = prev["up"] != 0
    cur_up = cur["up"] != 0
    lead_prev = (prev["role"] == LEADER) & prev_up
    lead_cur = (cur["role"] == LEADER) & cur_up
    new_leader = lead_cur & ~lead_prev
    restarted = cur_up & ~prev_up

    # Vote-grant baseline: phase 2's round start and phase F's restart both
    # zero the tally before this tick's grants land, so their delta floor
    # is 0, everyone else's is the pre-tick tally.
    new_round = cur["rounds"] > prev["rounds"]
    base_votes = jnp.where(new_round | restarted, 0,
                           prev["votes"].astype(_I32))
    d_votes = cur["votes"].astype(_I32) - base_votes

    # Pair-grid owner mask: the quirk-b win jump (next_index := commit + 1,
    # match_index := 0) and the restart wipe move the frontiers for
    # bookkeeping reasons — excluded from accept/reject accounting. Owner =
    # pair axis 0 (models/state.py [owner-1, peer-1, g]).
    owner_reset = (new_leader | restarted)[:, None, :]
    d_mi = cur["match_index"].astype(_I32) - prev["match_index"].astype(_I32)
    d_ni = cur["next_index"].astype(_I32) - prev["next_index"].astype(_I32)

    out = dict(tel)
    out["elections_started"] = tel["elections_started"] + _s(
        cur["rounds"] - prev["rounds"])
    out["leader_changes"] = tel["leader_changes"] + _s(new_leader)
    out["votes_granted"] = tel["votes_granted"] + _s(jnp.maximum(d_votes, 0))
    out["commit_advances"] = tel["commit_advances"] + _s(
        jnp.maximum(cur["commit"].astype(_I32) - prev["commit"].astype(_I32),
                    0))
    out["append_accepts"] = tel["append_accepts"] + _s(
        jnp.where(owner_reset, 0, jnp.maximum(d_mi, 0)))
    out["append_rejects"] = tel["append_rejects"] + _s(
        jnp.where(owner_reset, 0, jnp.maximum(-d_ni, 0)))
    out["fault_events"] = tel["fault_events"] + _s(prev_up != cur_up)
    if cur.get("vq_due") is not None:
        inflight = _s(cur["vq_due"] >= 0) + _s(cur["aq_due"] >= 0)
        out["mailbox_inflight_hw"] = jnp.maximum(
            tel["mailbox_inflight_hw"], inflight)
    if ov is not None:
        out["ov_fallbacks"] = tel["ov_fallbacks"] + ov.astype(_I32)
    return out


def state_view(state) -> dict:
    """The telemetry view of a RaftState (shared by every RaftState-carrying
    runner). Mailbox due slots included when present on the state."""
    v = {k: getattr(state, k) for k in TELEMETRY_STATE_FIELDS}
    for k in TELEMETRY_MAILBOX_FIELDS:
        v[k] = getattr(state, k, None)
    return v


def telemetry_step(prev_state, cur_state, tel: Dict[str, jax.Array],
                   ov: Optional[jax.Array] = None) -> Dict[str, jax.Array]:
    """telemetry_step_arrays over two RaftStates (one tick apart)."""
    return telemetry_step_arrays(
        state_view(prev_state), state_view(cur_state), tel, ov=ov)


def flat_view(flat: dict, n_nodes: int) -> dict:
    """The telemetry view of the flat rank-2 kernel/phase_body layout
    (ops/tick.flatten_state: node grids (N, G), pair grids (N*N, G)) —
    pair grids reshape to the canonical (N, N, G). Free in XLA; used by the
    Pallas flat-carry runner, which never materializes a RaftState between
    ticks."""
    N = n_nodes
    v = {}
    for k in TELEMETRY_STATE_FIELDS:
        a = flat[k]
        v[k] = a.reshape(N, N, -1) if k in ("match_index", "next_index") \
            else a
    for k in TELEMETRY_MAILBOX_FIELDS:
        a = flat.get(k)
        v[k] = a.reshape(N, N, -1) if a is not None else None
    return v


def summarize_telemetry(tel: Dict[str, jax.Array]) -> Dict[str, int]:
    """Host materialization of a recorder — the run's ONE device->host
    transfer for telemetry (a single batched device_get)."""
    host = jax.device_get(tel)
    return {k: int(host[k]) for k in TELEMETRY_FIELDS if k in host}


# ---------------------------------------------------------------------------
# Profiler scopes.

# The phase scope names, identical to opcount.phase_body_chain_depth
# (by_phase=True) attribution keys — a Perfetto trace groups ops under
# raft/<name> and the chain-depth model reports depth deltas under <name>,
# so the two line up column for column. "F0" covers the phase-F fault pass
# plus phase 0 (the same cut-0 boundary the attribution uses).
PHASE_SCOPES = ("F0", "p1", "p2", "p3", "p4", "p5")
SCOPE_PREFIX = "raft"


class PhaseScopes:
    """Sequential jax.named_scope manager for phase_body's LINEAR phase
    lattice: enter(name) closes the previous phase's scope and opens
    raft/<name>, so the 2000-line lattice gets phase-named HLO metadata
    without restructuring it into nested with-blocks. close() must run
    before every return (including the cut-truncated early returns).
    Trace-time metadata only — op names, never ops."""

    def __init__(self, prefix: str = SCOPE_PREFIX):
        self._prefix = prefix
        self._cm = None

    def enter(self, name: str) -> None:
        self.close()
        self._cm = jax.named_scope(f"{self._prefix}/{name}")
        self._cm.__enter__()

    def close(self) -> None:
        if self._cm is not None:
            self._cm.__exit__(None, None, None)
            self._cm = None


def engine_scope(name: str):
    """named_scope tagging one engine's tick ops (raft/engine/<name>) —
    names: xla, pallas, xla-fcache, shardmap-xla, shardmap-pallas,
    shardmap-fcache."""
    return jax.named_scope(f"{SCOPE_PREFIX}/engine/{name}")


@contextlib.contextmanager
def trace_span(name: str):
    """Host-side jax.profiler.TraceAnnotation for run-level regions (no-op
    when the profiler is unavailable). Use around whole dispatches, not
    inside jit — in-trace regions come from PhaseScopes/engine_scope."""
    try:
        ann = jax.profiler.TraceAnnotation(name)
    except Exception:  # profiler backend absent (some CPU wheels)
        yield
        return
    with ann:
        yield
