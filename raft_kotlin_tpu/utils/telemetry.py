"""Scan-carry flight recorder + phase/engine profiler scopes (ISSUE 5),
and the on-device Raft safety-invariant monitor (ISSUE 6 — see the
monitor section below: per-tick Figure-3 checks in the same scan carry,
a first-violation latch, sticky quirk-taint masks, and a downsampled
history ring).

The host-side observability path (utils/metrics.MetricsRecorder over
make_instrumented_run) is a per-window JSONL stream — right for dashboards,
wrong inside a production `jit`/`scan`/`shard_map` soak at 100k groups,
where per-tick (n_ticks,)-shaped metric outputs grow the scan's stacked
output arrays and anything host-visible forces a device sync. This module
is the ON-DEVICE recorder: a small fixed-shape pytree of scalar int32
counters accumulated INSIDE the tick/scan carry — per-tick health costs a
handful of fused (N, G)-wide reductions and is read back ONCE per run.

Every engine threads the same recorder through its carry:
- the XLA tick scan        (ops/tick.make_run(telemetry=True)),
- the Pallas flat-carry    (ops/pallas_tick.make_pallas_scan(telemetry=True)),
- the deep-log fc/batched  (ops/deep_cache.make_deep_scan /
                            make_sharded_deep_scan(telemetry=True)),
- the sharded runner       (parallel/mesh.make_sharded_run(telemetry=True)).

BIT-NEUTRALITY CONTRACT: the recorder reads ONLY the pre/post-tick states
the engines already produce — ops/tick.phase_body is never touched, so the
protocol bits are identical with the recorder on or off on every engine
(tests/test_telemetry.py pins this differentially across the sync,
mailbox, deep-log, int16, Pallas and sharded suites). For the Pallas
megakernel the accumulation runs on the flat scan carry BETWEEN kernel
launches (plain XLA reductions the fusion compiler folds), not inside
Mosaic: per-tile in-kernel partials would add output blocks and i1
reductions to a kernel whose bit-exactness is the project's core contract,
for no additional information — the flat carry already holds the same
post-tick values the tile wrote.

Counter semantics (all () int32, cumulative over the run; derived from
state TRANSITIONS, so they are engine-independent by construction):

- elections_started   sum of per-node `rounds` deltas (the ONE canonical
                      elections definition, shared with utils.metrics and
                      parallel.mesh).
- leader_changes      nodes that newly became LIVE leaders this tick
                      (role -> LEADER with up; a crashed leader's inert
                      role does not count).
- votes_granted       vote grants tallied this tick: positive `votes`
                      movement against a baseline of 0 for nodes that
                      started a round or restarted this tick (both reset
                      the tally before re-counting).
- commit_advances     sum of positive per-node commit deltas (quirk e can
                      legitimately LOWER a stale follower's commit; those
                      are not advances).
- append_accepts      match-frontier advance units: positive match_index
                      movement over pairs whose owner neither won an
                      election nor restarted this tick (both wipe the pair
                      row to 0 — bookkeeping, not replication).
- append_rejects      next_index decrements over the same owner mask (a
                      §6.2 reject walks next_index back exactly 1; the
                      quirk-b win jump and restart wipes are masked out).
- mailbox_inflight_hw high-water of the §10 in-flight slot count (vq/aq
                      slots with due >= 0, summed over pairs and groups);
                      0 on non-mailbox configs.
- ov_fallbacks        deep-engine frontier-cache overflow events: ticks
                      whose OV flag fired (the runner re-ran those bits on
                      the plain engine — time lost, never bits). 0 on
                      engines that carry no cache.
- fault_events        §9 liveness transitions (crashes + restarts).

Profiler scopes: PhaseScopes wraps ops/tick.phase_body's lattice in
`jax.named_scope` regions named exactly after the per-phase chain-depth
attribution keys (`opcount.phase_body_chain_depth(by_phase=True)`:
"F0", "p1" ... "p5", under the "raft/" prefix) so a Perfetto/TensorBoard
trace's op groups line up with the chain-depth model; `engine_scope` tags
each engine's tick, and `trace_span` is the host-side
jax.profiler.TraceAnnotation for run-level regions (scripts/
probe_telemetry.py). All three are trace-time metadata only — they name
HLO ops, they never add one.
"""

from __future__ import annotations

import contextlib
from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax

from raft_kotlin_tpu.constants import CANDIDATE, LEADER

_I32 = jnp.int32

# Canonical counter order (the recorder pytree's field set).
TELEMETRY_FIELDS = (
    "elections_started",
    "leader_changes",
    "votes_granted",
    "commit_advances",
    "append_accepts",
    "append_rejects",
    "mailbox_inflight_hw",
    "ov_fallbacks",
    "fault_events",
    # §15 compaction (r15): snapshot folds, InstallSnapshot applications,
    # and new capacity-exhaustion latches — all transition-derived, so
    # they stay engine-independent and 0 on non-compaction configs
    # (except cap_exhausted_events, which counts on every config).
    "snapshots_taken",
    "installsnap_deliveries",
    "cap_exhausted_events",
)

# The state fields one telemetry step reads (node grids (N, G) + pair grids
# (N, N, G) + optional §10 due slots) — the flat-carry runners build their
# views from exactly this list.
TELEMETRY_STATE_FIELDS = (
    "role", "up", "rounds", "votes", "commit", "match_index", "next_index",
    "last_index", "cap_ov",
)
TELEMETRY_MAILBOX_FIELDS = ("vq_due", "aq_due")
# §15: read when present (compaction configs only) — views supply None
# otherwise and the snapshot counters stay 0.
TELEMETRY_COMPACT_FIELDS = ("snap_index",)


def telemetry_zeros() -> Dict[str, jax.Array]:
    """A fresh recorder: every counter a () int32 zero."""
    return {k: jnp.zeros((), _I32) for k in TELEMETRY_FIELDS}


def _s(x) -> jax.Array:
    """Whole-array count/sum to a () int32 (bool or int input)."""
    return jnp.sum(x.astype(_I32))


def telemetry_step_arrays(prev: dict, cur: dict, tel: Dict[str, jax.Array],
                          ov: Optional[jax.Array] = None
                          ) -> Dict[str, jax.Array]:
    """One recorder step from pre/post-tick state VIEWS.

    `prev`/`cur` map TELEMETRY_STATE_FIELDS (plus TELEMETRY_MAILBOX_FIELDS
    when the config runs the §10 mailbox) to arrays in canonical RaftState
    shapes: node grids (N, G), pair grids (N, N, G), groups-minor. Bool
    fields may arrive as int stand-ins (the Pallas flat carry) — liveness
    is read as `!= 0`. `ov` is an optional () scalar (bool/int) counting a
    deep-engine cache-overflow event this tick. Returns the advanced
    recorder (a new dict; inputs untouched)."""
    prev_up = prev["up"] != 0
    cur_up = cur["up"] != 0
    lead_prev = (prev["role"] == LEADER) & prev_up
    lead_cur = (cur["role"] == LEADER) & cur_up
    new_leader = lead_cur & ~lead_prev
    restarted = cur_up & ~prev_up

    # Vote-grant baseline: phase 2's round start and phase F's restart both
    # zero the tally before this tick's grants land, so their delta floor
    # is 0, everyone else's is the pre-tick tally.
    new_round = cur["rounds"] > prev["rounds"]
    base_votes = jnp.where(new_round | restarted, 0,
                           prev["votes"].astype(_I32))
    d_votes = cur["votes"].astype(_I32) - base_votes

    # Pair-grid owner mask: the quirk-b win jump (next_index := commit + 1,
    # match_index := 0) and the restart wipe move the frontiers for
    # bookkeeping reasons — excluded from accept/reject accounting. Owner =
    # pair axis 0 (models/state.py [owner-1, peer-1, g]).
    owner_reset = (new_leader | restarted)[:, None, :]
    d_mi = cur["match_index"].astype(_I32) - prev["match_index"].astype(_I32)
    d_ni = cur["next_index"].astype(_I32) - prev["next_index"].astype(_I32)

    out = dict(tel)
    out["elections_started"] = tel["elections_started"] + _s(
        cur["rounds"] - prev["rounds"])
    out["leader_changes"] = tel["leader_changes"] + _s(new_leader)
    out["votes_granted"] = tel["votes_granted"] + _s(jnp.maximum(d_votes, 0))
    out["commit_advances"] = tel["commit_advances"] + _s(
        jnp.maximum(cur["commit"].astype(_I32) - prev["commit"].astype(_I32),
                    0))
    out["append_accepts"] = tel["append_accepts"] + _s(
        jnp.where(owner_reset, 0, jnp.maximum(d_mi, 0)))
    out["append_rejects"] = tel["append_rejects"] + _s(
        jnp.where(owner_reset, 0, jnp.maximum(-d_ni, 0)))
    out["fault_events"] = tel["fault_events"] + _s(prev_up != cur_up)
    # §15 capacity latch events: nodes whose cap_ov latched THIS tick.
    if cur.get("cap_ov") is not None:
        out["cap_exhausted_events"] = tel["cap_exhausted_events"] + _s(
            (cur["cap_ov"] != 0) & ~(prev["cap_ov"] != 0))
    # §15 snapshot counters, from snap_index transitions: a FOLD advances
    # snap_index while staying within the pre-tick readable log
    # (snap' <= li_prev) — EXCEPT the quirk-a case where commit outran the
    # node's own last_index and an aggressive fold pushes the base past li
    # (tick.py log_add's absorb note), which leaves li' < snap'. An INSTALL
    # jumps snap past everything the node had AND re-seats last_index at
    # the new base (li' >= snap' always — a post-install fold can't fire,
    # avail == 0), so the li' >= snap' test separates the two. A phase-F
    # restart wipes snap/log to 0 BEFORE this tick's deliveries land
    # (quirk l), so restarted nodes classify against the wiped baseline —
    # the same restart floor the vote/frontier deltas above use.
    if cur.get("snap_index") is not None:
        si_c = cur["snap_index"].astype(_I32)
        si_p = jnp.where(restarted, 0, prev["snap_index"].astype(_I32))
        li_p = jnp.where(restarted, 0, prev["last_index"].astype(_I32))
        adv = si_c > si_p
        inst = (adv & (si_c > li_p)
                & (si_c <= cur["last_index"].astype(_I32)))
        out["snapshots_taken"] = tel["snapshots_taken"] + _s(adv & ~inst)
        out["installsnap_deliveries"] = (tel["installsnap_deliveries"]
                                         + _s(inst))
    if cur.get("vq_due") is not None:
        inflight = _s(cur["vq_due"] >= 0) + _s(cur["aq_due"] >= 0)
        out["mailbox_inflight_hw"] = jnp.maximum(
            tel["mailbox_inflight_hw"], inflight)
    if ov is not None:
        out["ov_fallbacks"] = tel["ov_fallbacks"] + ov.astype(_I32)
    return out


def state_view(state) -> dict:
    """The telemetry view of a RaftState (shared by every RaftState-carrying
    runner). Mailbox due slots / §15 snapshot fields included when present
    on the state."""
    v = {k: getattr(state, k) for k in TELEMETRY_STATE_FIELDS}
    for k in TELEMETRY_MAILBOX_FIELDS + TELEMETRY_COMPACT_FIELDS:
        v[k] = getattr(state, k, None)
    return v


def telemetry_step(prev_state, cur_state, tel: Dict[str, jax.Array],
                   ov: Optional[jax.Array] = None) -> Dict[str, jax.Array]:
    """telemetry_step_arrays over two RaftStates (one tick apart)."""
    return telemetry_step_arrays(
        state_view(prev_state), state_view(cur_state), tel, ov=ov)


def flat_view(flat: dict, n_nodes: int) -> dict:
    """The telemetry view of the flat rank-2 kernel/phase_body layout
    (ops/tick.flatten_state: node grids (N, G), pair grids (N*N, G)) —
    pair grids reshape to the canonical (N, N, G). Free in XLA; used by the
    Pallas flat-carry runner, which never materializes a RaftState between
    ticks."""
    N = n_nodes
    v = {}
    for k in TELEMETRY_STATE_FIELDS:
        a = flat[k]
        v[k] = a.reshape(N, N, -1) if k in ("match_index", "next_index") \
            else a
    for k in TELEMETRY_MAILBOX_FIELDS:
        a = flat.get(k)
        v[k] = a.reshape(N, N, -1) if a is not None else None
    for k in TELEMETRY_COMPACT_FIELDS:
        v[k] = flat.get(k)
    return v


def summarize_telemetry(tel: Dict[str, jax.Array]) -> Dict[str, int]:
    """Host materialization of a recorder — the run's ONE device->host
    transfer for telemetry (a single batched device_get)."""
    host = jax.device_get(tel)
    return {k: int(host[k]) for k in TELEMETRY_FIELDS if k in host}


# ---------------------------------------------------------------------------
# On-device Raft safety-invariant monitor (ISSUE 6).
#
# Per-tick vectorized checks of the Figure-3 safety properties (Ongaro &
# Ousterhout 2014) accumulated in the scan carry of every engine, exactly
# like the flight recorder above: each invariant is a pre/post-tick STATE
# reduction, so it is engine-independent and bit-neutral by construction
# (the monitor only reads the states the scans already carry — phase_body
# is never touched). The carry holds:
#
# - a first-violation LATCH: the lexicographically earliest
#   (tick, group, invariant_id) of the run, device-resident (-1 = clean),
# - per-invariant violation counts,
# - two sticky per-group TAINT masks that encode where the classical
#   Figure-3 proofs stop applying to the REFERENCE's quirk semantics
#   (SEMANTICS.md §8/§11 — the implemented invariants are quirk-aware):
#   * taint_restart — some node restarted since boot (quirk l: no
#     persistence; a restart wipes votedFor/log, which the Election
#     Safety / Log Matching / Leader Completeness proofs all require),
#   * taint_unsafe_commit — a live leader's commit advance topped out on
#     an entry NOT of its current term (quirk a has no current-term
#     commit guard; this is exactly the Figure-8 hazard of the paper,
#     §5.4.2, after which committed-prefix durability is classically
#     unjustified). NOT sticky: a later commit advance topping out on a
#     CURRENT-term entry re-justifies the whole prefix (the paper's
#     indirect-commit rule) and clears it,
# - a downsampled HISTORY RING: W windows of key health signals (group
#   commit-frontier min/max, live-leader count, §10 in-flight high-water,
#   violation count), giving a post-mortem timeline with zero per-tick
#   host transfers.
#
# Invariant ids (INVARIANT_IDS order is the latch's tie-break order):
#
# 0 election_safety    ≤1 live leader per (term, group). Exempt: groups
#                      with taint_restart (a restarted voter re-grants a
#                      term its pre-restart self already voted in).
# 1 leader_append_only a node that is a live leader in BOTH states with
#                      the SAME term never changes the stored content of
#                      a slot below min(prev, cur) last_index. CONTENT
#                      form: the readable window may shrink — quirk b/c
#                      stale self-appends re-add the leader's own entry
#                      (identical bits) at next_index-1, a §3 overwrite.
#                      Self-exempting (restart/demotion clears the
#                      continuing-leader mask); no taint gate.
# 2 log_matching       same (index, term) on two PRISTINE logs implies
#                      identical entries up to and including that index.
#                      Pristine = phys_len == last_index (never truncated):
#                      quirk j physically retains a truncated tail and
#                      later re-exposes stale slots, which the reference
#                      itself then serves — ghost logs are not comparable.
#                      Exempt: taint_restart (split-brain same-term
#                      leaders can mint conflicting same-term entries).
# 3 leader_completeness every live leader's log CONTAINS (entry-for-entry)
#                      every node's readable committed prefix
#                      min(commit, last_index). Pristine endpoints only;
#                      exempt: taint_restart, taint_unsafe_commit, and
#                      the per-tick stale-append hazard window (a live
#                      non-leader with an armed heartbeat, or a §10
#                      in-flight append slot owned by a non-leader —
#                      quirk-d stale appends legitimately rewrite
#                      followers then).
# 4 commit_monotonic   the GROUP commit frontier max_n(commit) never
#                      decreases, with nodes restarting THIS tick masked
#                      out of the prev-side max (quirk l wipes commit; a
#                      quirk-e lowering can never reach the frontier
#                      holder, so the group form needs no quirk-e gate —
#                      the per-node form would). State Machine Safety (a).
# 5 committed_prefix   per node: the STORED content below the pre-tick
#                      readable committed prefix min(commit, last_index)
#                      never changes (CONTENT form — readability may
#                      shrink via quirk-b/c stale self-appends; §3
#                      retains and later re-exposes the original bits).
#                      Exempt: the node restarting this tick,
#                      taint_restart, taint_unsafe_commit (Figure 8 is
#                      precisely a rewrite below a quirk-a commit), and
#                      the stale-append hazard window (see id 3).
#                      State Machine Safety (b).
#
# SEMANTICS.md §11 states each check formally; tests/test_invariants.py
# pins bit-neutrality, host-vs-device latch equality, and exact-coordinate
# latching of injected violations.

INVARIANT_IDS = (
    "election_safety",
    "leader_append_only",
    "log_matching",
    "leader_completeness",
    "commit_monotonic",
    "committed_prefix",
    # 6 (§15, compaction configs only — structurally clean otherwise):
    # two nodes with EQUAL nonzero snap_index folded the same committed
    # prefix, so their (snap_term, snap_digest) must be bit-equal. The
    # entry-wise checks (2/3/5) stop at the snapshot boundary; this is
    # the check that extends Log Matching / State Machine Safety ACROSS
    # the truncation boundary. Gates: taint_restart, taint_unsafe, the
    # stale-append hazard window, and any capacity-latched group (a
    # clipped log legitimately folds §3 stale-slot content).
    "snapshot_consistency",
)
N_INVARIANTS = len(INVARIANT_IDS)

# History-ring geometry: W windows per run; the runner picks the stride so
# the W windows tile the run (monitor_ring_stride). Signals per window:
# commit_min/commit_max (min/max over the window of the cross-group
# min/max of the group commit frontier), leaders (peak live-leader count),
# inflight_hw (§10 slot high-water), violations (sum).
MONITOR_WINDOWS = 32
RING_SIGNALS = ("commit_min", "commit_max", "leaders", "inflight_hw",
                "violations")
_RING_BIG = jnp.iinfo(jnp.int32).max

# State fields one monitor step reads (canonical shapes: node grids (N, G),
# logs (N, C, G); plus TELEMETRY_MAILBOX_FIELDS when the config runs §10).
# hb_armed feeds the stale-append hazard window (see invariant_matrix);
# cap_ov gates snapshot_consistency on capacity-clipped groups.
MONITOR_STATE_FIELDS = ("role", "up", "term", "commit", "last_index",
                        "phys_len", "hb_armed", "log_term", "log_cmd",
                        "cap_ov")
# §15 snapshot fields: read when present (compaction configs) — the
# position-based ring addressing and invariant 6 switch on their presence.
MONITOR_COMPACT_FIELDS = ("snap_index", "snap_term", "snap_digest")


def monitor_ring_stride(n_ticks: int, windows: int = MONITOR_WINDOWS) -> int:
    """Ticks per history-ring window so `windows` windows tile a run of
    n_ticks (the last window may be partial)."""
    return max(1, -(-int(n_ticks) // int(windows)))


# ---------------------------------------------------------------------------
# §21 streaming ops plane — channel/kind tables (SEMANTICS.md §21).
#
# The SERIES ring generalizes the 5-signal history ring above into a
# configurable multi-channel (W, K) int32 block: one column per channel,
# one row per window of `series_stride` ticks, each cell folded per tick
# with the channel's combine op from the channel's identity at window
# entry. Channels are pre/post-tick state-transition reductions (plus, for
# the srv_* columns, reductions over serving-CARRY pairs — observers of an
# observer, one level up the same contract), so the block is bit-neutral
# and engine-independent exactly like the recorder: integer sums/extrema
# ⇒ sharded ≡ single-device bits, fused-T replay ≡ T=1 by construction.
#
# Column order is SERIES_CHANNELS order; each entry is
# (name, combine, identity):
# - sum channels mirror the flight-recorder counter DELTAS per window
#   (elections = rounds deltas, leader_changes, commit_advances,
#   fault_events, snapshot folds / InstallSnapshot deliveries) plus the
#   monitor's per-tick violation count — the grp_* farm stress aggregates
#   cross-group summed into the timeline;
# - gauge channels window-extremize the frontier/health signals
#   (group commit-frontier min/max, live-leader peak, leaderless-group
#   peak, §10 in-flight peak);
# - srv_* channels summarize the §20 serving carry per window: applied /
#   served-read deltas, the applied-frontier peak, the read-queue peak,
#   and the submit→apply / read latency histograms' running summaries
#   (per-window count is srv_applied/srv_reads; sum and max derive from
#   the width-1 histogram bin deltas — exact up to the hist's own
#   last-bin overflow clamp). 0/identity when the runner carries no
#   serving dict.
SERIES_CHANNELS = (
    ("elections", "sum", 0),
    ("leader_changes", "sum", 0),
    ("commit_advances", "sum", 0),
    ("fault_events", "sum", 0),
    ("violations", "sum", 0),
    ("snapshot_folds", "sum", 0),
    ("installsnap", "sum", 0),
    ("srv_applied", "sum", 0),
    ("srv_reads", "sum", 0),
    ("srv_commit_lat_sum", "sum", 0),
    ("srv_read_lat_sum", "sum", 0),
    ("commit_max", "max", -1),
    ("leaders_hw", "max", 0),
    ("down_groups_hw", "max", 0),
    ("inflight_hw", "max", 0),
    ("srv_read_q_hw", "max", 0),
    ("srv_commit_lat_max", "max", -1),
    ("srv_read_lat_max", "max", -1),
    ("srv_applied_frontier", "max", 0),
    ("commit_min", "min", _RING_BIG),
)
SERIES_NAMES = tuple(c[0] for c in SERIES_CHANNELS)
N_SERIES = len(SERIES_CHANNELS)

# The EVENT ring: a bounded encoded event stream derived from the same
# transition reductions — the FIRST `event_capacity` events of the run as
# (kind, tick, group, arg) int32 rows, then a loud `events_dropped`
# counter (the first-violation latch generalized: a latch IS an event
# ring of capacity 1; the per-tick write order is the same lexicographic
# (kind, group) key the latch's masked-min uses, realized as a cumsum
# ordinal so multiple same-tick events land in deterministic order).
# Per-kind args (all group-scoped; universe ADMIT events are host-side —
# the admission loop appends them to the decoded stream from its
# admit_log, api/fuzz.continuous_farm):
#   leader_change     arg = lowest node index that newly became live leader
#   election_start    arg = vote rounds started in the group this tick
#   election_resolve  arg = max term among the restored live leaders
#   snapshot_fold     arg = highest snap_index folded to this tick
#   installsnap       arg = highest snap_index installed this tick
#   cap_latch         arg = nodes newly capacity-latched (§15/§16 cap_ov;
#                     the packed width latch is a host-side sibling —
#                     engines surface it outside the carry)
#   retire            arg = retirement age (§19 sched channel only)
#   violation         arg = lowest violated invariant id this tick
EVENT_KINDS = (
    "leader_change",
    "election_start",
    "election_resolve",
    "snapshot_fold",
    "installsnap",
    "cap_latch",
    "retire",
    "violation",
)
N_EVENT_KINDS = len(EVENT_KINDS)


def ops_kw(cfg) -> dict:
    """The §21 monitor_init kwargs of a RaftConfig — the one-liner every
    engine's scan builder splices in (`**telemetry.ops_kw(cfg)`), so the
    ops-plane channels ride whatever engine the plan routes without
    engine-specific wiring."""
    return {"series": int(getattr(cfg, "series_windows", 0) or 0),
            "series_stride": int(getattr(cfg, "series_stride", 0) or 0),
            "events": int(getattr(cfg, "event_capacity", 0) or 0)}


def monitor_init(n_groups: int, n_ticks: int, enabled: bool = True,
                 per_group: bool = False, timing: bool = False,
                 sched: bool = False, quiesce_ticks: int = 0,
                 series: int = 0, series_stride: int = 0, events: int = 0
                 ) -> Optional[Dict[str, jax.Array]]:
    """THE runner-side monitor-carry constructor: a fresh carry with the
    ring stride tiling an n_ticks run, or None when the runner's monitor
    flag is off — one copy of the idiom every engine's scan builder uses,
    so the carry's construction can never drift between engines.
    `per_group=True` adds the PER_GROUP_KEYS stress counters (the fuzzing
    farm's universe-ranking channel — reduced in the carry alongside the
    history ring, zero per-tick host traffic). `timing=True` adds the §19
    downtime/election-latency histogram channel; `sched=True` the §19
    retirement-predicate channel with quiescence horizon `quiesce_ticks`
    (both per-group — see monitor_zeros). `series`/`events` are the §21
    ops-plane channels (SERIES_CHANNELS / EVENT_KINDS; 0 = off):
    `series` windows of `series_stride` ticks (0 = auto-tile the run like
    the history ring) and an event ring of capacity `events` — engines
    splice both from the config via `**ops_kw(cfg)`."""
    if not enabled:
        return None
    if series > 0 and series_stride <= 0:
        series_stride = monitor_ring_stride(n_ticks, series)
    return monitor_zeros(n_groups, monitor_ring_stride(n_ticks),
                         per_group=per_group, timing=timing, sched=sched,
                         quiesce_ticks=quiesce_ticks, series=series,
                         series_stride=series_stride, events=events)


# Per-group (universe) stress counters, carried when monitor_zeros(
# per_group=True): elections started (rounds delta), §9 liveness
# transitions, and per-group violation counts — the fuzzing farm ranks
# universes by these without any host readback (api/fuzz.py). grp_elections
# needs `rounds` in the step views; monitor_view/monitor_flat_view supply
# it opportunistically and monitor_step_arrays raises if a per-group carry
# meets a view without it (a fused-snapshot path misconfiguration).
PER_GROUP_KEYS = ("grp_elections", "grp_fault_events", "grp_violations")

# §19 timing-observatory channel (timing=True): fixed-bin int32 histograms
# accumulated IN the carry — same transport contract as the history ring
# (static shapes, integer sums, one readback; order-independent, so a
# sharded run's psum'd histogram is bit-equal to single-device). Bins are
# width-1 tick counts with the last bin absorbing overflow. hist_downtime
# bins completed leaderless runs at the tick leadership returns;
# hist_elect bins the candidate-active sub-run of the same outage (the
# §9.3 election-latency figure); down_ticks totals leaderless group-ticks.
TIMING_BINS = 64
TIMING_KEYS = ("hist_downtime", "hist_elect", "down_ticks",
               "grp_down_run", "grp_elect_run")

# §19 continuous-scheduler channel (sched=True): the per-group retirement
# predicate evaluated in the carry. grp_retire_age latches the group's age
# at FIRST retirement (-1 = live; sticky), the (G,) retire mask the
# admission loop reads is simply grp_retire_age >= 0. Arms: violation this
# tick / lifetime horizon reached (grp_life, 0 = unbounded — installed
# from the bank's "life" row by the runner) / quiescence (sched_quiesce
# consecutive calm ticks: live leader, no election activity, no fault
# transitions; 0 disables).
SCHED_KEYS = ("grp_age", "grp_life", "grp_calm", "grp_retire_age",
              "sched_quiesce")
# The carry rows the admission loop re-seeds across segment boundaries
# (cleared under the reset mask, carried elsewhere).
SCHED_SEED_KEYS = ("grp_age", "grp_calm", "grp_down_run", "grp_elect_run")


def monitor_zeros(n_groups: int, ring_stride: int = 1,
                  windows: int = MONITOR_WINDOWS,
                  per_group: bool = False, timing: bool = False,
                  sched: bool = False, quiesce_ticks: int = 0,
                  bins: int = TIMING_BINS, series: int = 0,
                  series_stride: int = 1, events: int = 0
                  ) -> Dict[str, jax.Array]:
    """A fresh monitor carry. `ring_stride` is baked in as a () int32 so
    summarize_monitor can decode the ring without out-of-band metadata.
    `timing`/`sched` add the §19 channels (see TIMING_KEYS/SCHED_KEYS);
    `series`/`events` the §21 ops-plane rings (strides baked in like
    ring_stride, so the decoders need no out-of-band metadata either)."""
    neg1 = jnp.full((), -1, _I32)
    out = {
        "tick": jnp.zeros((), _I32),
        "latch_tick": neg1, "latch_group": neg1, "latch_inv": neg1,
        "viol_total": jnp.zeros((), _I32),
        "viol_by_inv": jnp.zeros((N_INVARIANTS,), _I32),
        "taint_restart": jnp.zeros((n_groups,), dtype=bool),
        "taint_unsafe": jnp.zeros((n_groups,), dtype=bool),
        "ring_commit_min": jnp.full((windows,), _RING_BIG, _I32),
        "ring_commit_max": jnp.full((windows,), -1, _I32),
        "ring_leaders": jnp.zeros((windows,), _I32),
        "ring_inflight_hw": jnp.zeros((windows,), _I32),
        "ring_violations": jnp.zeros((windows,), _I32),
        "ring_stride": jnp.full((), int(ring_stride), _I32),
    }
    if per_group:
        for k in PER_GROUP_KEYS:
            out[k] = jnp.zeros((n_groups,), _I32)
    if timing:
        out["hist_downtime"] = jnp.zeros((bins,), _I32)
        out["hist_elect"] = jnp.zeros((bins,), _I32)
        out["down_ticks"] = jnp.zeros((), _I32)
        out["grp_down_run"] = jnp.zeros((n_groups,), _I32)
        out["grp_elect_run"] = jnp.zeros((n_groups,), _I32)
    if sched:
        out["grp_age"] = jnp.zeros((n_groups,), _I32)
        out["grp_life"] = jnp.zeros((n_groups,), _I32)
        out["grp_calm"] = jnp.zeros((n_groups,), _I32)
        out["grp_retire_age"] = jnp.full((n_groups,), -1, _I32)
        out["sched_quiesce"] = jnp.full((), int(quiesce_ticks), _I32)
    if series > 0:
        # §21 series ring: every cell starts at its channel's identity so
        # never-entered windows decode as "no data" without a used mask
        # (the same convention as the history ring's identity slots).
        idents = jnp.asarray([c[2] for c in SERIES_CHANNELS], _I32)
        out["series_data"] = jnp.broadcast_to(
            idents, (int(series), N_SERIES)).astype(_I32)
        out["series_stride"] = jnp.full((), int(max(1, series_stride)),
                                        _I32)
    if events > 0:
        # §21 event ring: kind -1 marks an unwritten row; ev_count is the
        # total ATTEMPTED (the cursor), events_dropped the loud overflow.
        out["ev_kind"] = jnp.full((int(events),), -1, _I32)
        out["ev_tick"] = jnp.full((int(events),), -1, _I32)
        out["ev_grp"] = jnp.full((int(events),), -1, _I32)
        out["ev_arg"] = jnp.zeros((int(events),), _I32)
        out["ev_count"] = jnp.zeros((), _I32)
        out["events_dropped"] = jnp.zeros((), _I32)
    return out


def invariant_matrix(prev: dict, cur: dict, taint_restart: jax.Array,
                     taint_unsafe: jax.Array):
    """The per-tick verdicts: (V, taint_restart', taint_unsafe') where V is
    a (N_INVARIANTS, G) bool matrix of per-group violations for the
    transition prev -> cur, with the quirk exemptions above already
    applied (taints are updated FIRST, so a restart enabling a same-tick
    violation exempts it — SEMANTICS.md §11). `prev`/`cur` map
    MONITOR_STATE_FIELDS (+ mailbox dues, unread here) to canonical-shape
    arrays; bool fields may arrive as int stand-ins (the Pallas flat
    carry). THE single source of truth for the Figure-3 checks — the
    host-side path (utils/metrics.figure3_counts) and every engine carry
    call exactly this function."""
    lt_p, lc_p = prev["log_term"], prev["log_cmd"]
    lt_c, lc_c = cur["log_term"], cur["log_cmd"]
    N, C, G = lt_c.shape
    slot = lax.broadcasted_iota(_I32, (C, G), 0)

    prev_up = prev["up"] != 0
    cur_up = cur["up"] != 0
    restarted = cur_up & ~prev_up                       # (N, G)
    lead_p = (prev["role"] == LEADER) & prev_up
    lead = (cur["role"] == LEADER) & cur_up
    term_p = prev["term"].astype(_I32)
    term = cur["term"].astype(_I32)
    li_p = prev["last_index"].astype(_I32)
    li_c = cur["last_index"].astype(_I32)
    cm_p = prev["commit"].astype(_I32)
    cm_c = cur["commit"].astype(_I32)

    # §15 ring addressing (compaction configs — snap_index present): slot
    # s of a node with base b stores the unique position p ≡ s (mod C)
    # inside the live window [b, b + C). Entry-wise checks then compare
    # POSITIONS (two nodes' same slot holds the same position only where
    # their windows overlap), and the folded prefix below max(bases) is
    # covered by invariant 6 (snapshot_consistency) instead.
    si_c = cur.get("snap_index")
    compacted = si_c is not None
    if compacted:
        b_c = si_c.astype(_I32)
        b_p = prev["snap_index"].astype(_I32)
        st_c = cur["snap_term"].astype(_I32)

        def pos_of(b_n):
            return b_n[None] + jnp.remainder(slot - b_n[None], C)

    # Taints, updated before the gated checks (see docstring). The restart
    # taint is sticky for the run; the unsafe-commit taint follows the
    # paper's §5.4.2 rule exactly: a quirk-a commit whose TOP newly
    # committed slot holds an OLD term is the Figure-8 hazard (sets the
    # taint), while a commit advance topping out on a CURRENT-term entry
    # re-justifies the entire prefix below it (clears the taint) — the
    # classical indirect-commit argument, which re-arms the durability
    # checks once a live leader commits an entry of its own term.
    taint_restart = taint_restart | jnp.any(restarted, axis=0)
    adv = (cm_c > cm_p) & lead & ~restarted
    unsafe = jnp.zeros((G,), dtype=bool)
    justify = jnp.zeros((G,), dtype=bool)
    for n in range(N):
        if compacted:
            top = jnp.sum(jnp.where(pos_of(b_c[n]) == cm_c[n][None] - 1,
                                    lt_c[n], 0), axis=0).astype(_I32)
            # Fully folded committed prefix: the top committed entry IS
            # the snapshot boundary — its term is snap_term.
            top = jnp.where(cm_c[n] == b_c[n], st_c[n], top)
        else:
            top = jnp.sum(jnp.where(slot == cm_c[n][None] - 1,
                                    lt_c[n], 0), axis=0).astype(_I32)
        top_cur = top == term[n]
        unsafe = unsafe | (adv[n] & ~top_cur)
        justify = justify | (adv[n] & top_cur)
    taint_unsafe = (taint_unsafe | unsafe) & ~(justify & ~unsafe)

    # Stale-append hazard window (per-tick, transient — not a taint): a
    # DEMOTED leader's still-armed heartbeat fires one last full append
    # round, and a CANDIDATE ex-leader keeps heartbeating (§5/§8 — the
    # cancel guard checks FOLLOWER only); under §10, in-flight append
    # slots from a deposed owner deliver late. Either way a NON-leader
    # sender can legitimately overwrite a follower's committed/matched
    # entries with stale content (quirk d never rejects on term). The
    # cross-node durability checks (3, 5) are masked while such a sender
    # exists; log_matching survives unmasked (the stale entry keeps its
    # old term, and the victim's truncation de-pristines it).
    hb = prev.get("hb_armed")
    hazard = jnp.zeros((G,), dtype=bool)
    if hb is not None:
        hazard = jnp.any((hb != 0) & prev_up
                         & (prev["role"] != LEADER), axis=0)
    if prev.get("aq_due") is not None:
        stale_slot = (prev["aq_due"] >= 0) & ~lead_p[:, None, :]
        hazard = hazard | jnp.any(stale_slot, axis=(0, 1))

    # 0 — Election Safety: two live leaders sharing a term.
    two_lead = jnp.zeros((G,), dtype=bool)
    for a in range(N):
        for b in range(a + 1, N):
            two_lead = two_lead | (lead[a] & lead[b] & (term[a] == term[b]))
    v0 = two_lead & ~taint_restart

    # 1 — Leader Append-Only, CONTENT form: a continuing same-term live
    # leader never changes the stored content of a slot below its readable
    # window. The window itself may SHRINK: a stale self-append (quirk b
    # inits next_index[self] to commit+1 < last_index) re-adds the
    # leader's own entry at next_index-1, which is a §3 overwrite — same
    # bits, lower last_index — and the reference does this routinely on
    # the tick after every election win (and, under §10, τ ticks later).
    cont = lead & lead_p & (term == term_p)
    v1 = jnp.zeros((G,), dtype=bool)
    for n in range(N):
        if compacted:
            # Compare per POSITION: a slot whose position changed between
            # ticks was recycled by the sliding window, not rewritten.
            pc, pp = pos_of(b_c[n]), pos_of(b_p[n])
            keep = (pc == pp) & (pc < jnp.minimum(li_p[n], li_c[n])[None])
        else:
            keep = slot < jnp.minimum(li_p[n], li_c[n])[None]
        changed = jnp.any(
            keep & ((lt_p[n] != lt_c[n]) | (lc_p[n] != lc_c[n])), axis=0)
        v1 = v1 | (cont[n] & changed)

    # Quirk-j ghost exemption for the cross-node prefix compares: a log
    # that has EVER truncated keeps phys_len > last_index for the rest of
    # the node's lifetime (append moves both; only restart rezeroes), so
    # pristine == "no stale physical tail exists to be re-exposed".
    pristine = cur["phys_len"].astype(_I32) == li_c    # (N, G)

    # 2/3 — Log Matching + Leader Completeness share the pairwise
    # entry-mismatch tensors (one (C, G) compare pair per unordered node
    # pair; N <= 9, unrolled at trace time like the tick's own pair loops).
    rc = jnp.minimum(cm_c, li_c)                       # readable committed
    v2 = jnp.zeros((G,), dtype=bool)
    v3 = jnp.zeros((G,), dtype=bool)
    for a in range(N):
        for b in range(a + 1, N):
            mism = (lt_c[a] != lt_c[b]) | (lc_c[a] != lc_c[b])   # (C, G)
            both = jnp.minimum(li_c[a], li_c[b])[None]
            if compacted:
                # Comparable slots: the position is in BOTH live windows
                # (pa == pb ⇔ the position lies in the window overlap
                # [max(bases), min(bases) + C)).
                pa, pb = pos_of(b_c[a]), pos_of(b_c[b])
                shared = pa == pb
                valid = shared & (pa < both)
                # Position-ordered inclusive prefix over the RING: the
                # overlap starts at position lo = max(bases) = ring slot
                # lo mod C, so a position interval [lo, p] is the slot
                # interval [lo mod C, p mod C] — possibly WRAPPED. One
                # slot-order cumsum + the wrap algebra recovers the
                # position-ordered prefix counts.
                lo = jnp.maximum(b_c[a], b_c[b])       # (G,)
                cs = jnp.cumsum((mism & valid).astype(_I32), axis=0)
                lmod = jnp.remainder(lo, C)            # (G,)
                s_lm1 = jnp.where(
                    lmod > 0,
                    jnp.take_along_axis(
                        cs, jnp.clip(lmod - 1, 0, C - 1)[None],
                        axis=0)[0],
                    0)
                pref = jnp.where(slot >= lmod[None], cs - s_lm1[None],
                                 cs[C - 1][None] - s_lm1[None] + cs)
                bad_pref = pref > 0
            else:
                valid = slot < both
                # Inclusive prefix-mismatch: an entry with matching terms
                # at i demands identical entries at ALL j <= i (cmd
                # included).
                bad_pref = jnp.cumsum((mism & valid).astype(_I32),
                                      axis=0) > 0
            v2 = v2 | (pristine[a] & pristine[b] & jnp.any(
                valid & (lt_c[a] == lt_c[b]) & bad_pref, axis=0))
            for l, n in ((a, b), (b, a)):
                lim = jnp.minimum(rc[n], li_c[l])[None]
                if compacted:
                    # Entry-wise containment only over the window overlap;
                    # the follower's committed prefix below the leader's
                    # base is folded on the leader — covered by invariant
                    # 6, not comparable entry-wise (and not a violation).
                    pl_, pn_ = (pa, pb) if l == a else (pb, pa)
                    diff = jnp.any(mism & (pl_ == pn_) & (pl_ < lim),
                                   axis=0)
                else:
                    diff = jnp.any(mism & (slot < lim), axis=0)
                v3 = v3 | (lead[l] & pristine[l] & pristine[n]
                           & ~restarted[n]
                           & ((rc[n] > li_c[l]) | diff))
    v2 = v2 & ~taint_restart
    v3 = v3 & ~taint_restart & ~taint_unsafe & ~hazard

    # 4 — group commit-frontier monotonicity (restart-masked prev side).
    fr_prev = jnp.max(jnp.where(restarted, 0, cm_p), axis=0)
    v4 = jnp.max(cm_c, axis=0) < fr_prev

    # 5 — committed-prefix immutability per node, CONTENT form: the
    # STORED content of every slot below the pre-tick readable committed
    # prefix rc = min(commit, last_index) never changes. Readability of
    # those slots is NOT asserted: a stale self-append (see inv 1) can
    # legitimately truncate the leader's readable window below its own
    # commit; §3 retains the physical slots, and later ghost appends
    # re-expose the ORIGINAL bits — content is what survives quirks b/c/j,
    # so content is what the implemented invariant protects. A genuine
    # Figure-8 overwrite rewrites the bits and is caught (when the group
    # is untainted; quirk-a old-term commits set taint_unsafe first).
    v5 = jnp.zeros((G,), dtype=bool)
    for n in range(N):
        if compacted:
            # Position-based content form (see v1): slots the sliding
            # window recycled this tick carry NEW positions — the old
            # position's content is in the snapshot digest (invariant 6).
            pc, pp = pos_of(b_c[n]), pos_of(b_p[n])
            keep = (pc == pp) & (pp < jnp.minimum(cm_p[n], li_p[n])[None])
        else:
            keep = slot < jnp.minimum(cm_p[n], li_p[n])[None]
        changed = jnp.any(
            keep & ((lt_p[n] != lt_c[n]) | (lc_p[n] != lc_c[n])), axis=0)
        v5 = v5 | (~restarted[n] & changed)
    v5 = v5 & ~taint_restart & ~taint_unsafe & ~hazard

    # 6 — snapshot consistency (§15, compaction only): equal nonzero
    # snap_index ⇒ identical (snap_term, snap_digest) — the cross-node
    # durability check that survives the truncation boundary. Gated like
    # 3/5, plus capacity-latched groups (a §3 clip makes later folds read
    # stale ring content — canonical garbage, deterministic per engine
    # but not cross-node comparable).
    v6 = jnp.zeros((G,), dtype=bool)
    if compacted:
        dg_c = cur["snap_digest"].astype(_I32)
        cap = cur.get("cap_ov")
        cap_any = (jnp.any(cap != 0, axis=0) if cap is not None
                   else jnp.zeros((G,), dtype=bool))
        for a in range(N):
            for b in range(a + 1, N):
                eq = (b_c[a] == b_c[b]) & (b_c[a] > 0)
                v6 = v6 | (eq & ((st_c[a] != st_c[b])
                                 | (dg_c[a] != dg_c[b])))
        v6 = (v6 & ~taint_restart & ~taint_unsafe & ~hazard & ~cap_any)

    V = jnp.stack([
        v0.astype(_I32), v1.astype(_I32), v2.astype(_I32),
        v3.astype(_I32), v4.astype(_I32), v5.astype(_I32),
        v6.astype(_I32)]) != 0
    return V, taint_restart, taint_unsafe


def monitor_step_arrays(prev: dict, cur: dict, mon: Dict[str, jax.Array],
                        srv_prev: Optional[dict] = None,
                        srv_cur: Optional[dict] = None
                        ) -> Dict[str, jax.Array]:
    """One monitor step from pre/post-tick state VIEWS: run the checks,
    fold the verdicts into latch/counters/taints, and advance the history
    ring (and, when the carry holds them, the §21 series/event rings).
    Returns the advanced carry (a new dict; inputs untouched).
    `srv_prev`/`srv_cur` are the pre/post §20 serving-carry pair for this
    tick — runners that advance serving pass them so the srv_* series
    columns fill; None leaves those columns at their identities."""
    V, tr, tu = invariant_matrix(prev, cur, mon["taint_restart"],
                                 mon["taint_unsafe"])
    out = dict(mon)
    out["taint_restart"], out["taint_unsafe"] = tr, tu
    tick = mon["tick"]
    per_inv = jnp.sum(V.astype(_I32), axis=1)          # (N_INVARIANTS,)
    vc = jnp.sum(per_inv)
    out["viol_by_inv"] = mon["viol_by_inv"] + per_inv
    out["viol_total"] = mon["viol_total"] + vc

    if "grp_violations" in mon:
        # Per-group (universe) stress counters (PER_GROUP_KEYS): the same
        # transition reductions as the latch/flight-recorder, kept (G,)-
        # wide in the carry so the farm ranks universes with zero per-tick
        # host traffic.
        out["grp_violations"] = mon["grp_violations"] + jnp.sum(
            V.astype(_I32), axis=0)
        out["grp_fault_events"] = mon["grp_fault_events"] + jnp.sum(
            ((prev["up"] != 0) != (cur["up"] != 0)).astype(_I32), axis=0)
        r_p, r_c = prev.get("rounds"), cur.get("rounds")
        if r_p is None or r_c is None:
            raise ValueError(
                "per-group monitor counters need `rounds` in the step "
                "views (monitor_view/monitor_flat_view supply it; a fused "
                "snapshot set does not — run the farm on a full-state "
                "engine)")
        out["grp_elections"] = mon["grp_elections"] + jnp.sum(
            r_c.astype(_I32) - r_p.astype(_I32), axis=0)

    if "grp_down_run" in mon or "grp_age" in mon:
        # §19 leadership view shared by the timing and scheduler channels:
        # does the group have a live leader POST-tick?
        lead_c = jnp.any((cur["role"] == LEADER) & (cur["up"] != 0), axis=0)

    if "grp_down_run" in mon:
        # §19 timing observatory: run-length counters advance per tick; a
        # completed run bins into the carry-resident histogram ON the tick
        # leadership returns (that tick itself is not leaderless). Exactly
        # recomputable from a (T, N, G) role/up trace —
        # tests/test_scheduler.py pins the recomputation bit-for-bit.
        down_run = mon["grp_down_run"]
        elect_run = mon["grp_elect_run"]
        B = mon["hist_downtime"].shape[0]
        rec = lead_c & (down_run > 0)

        def bump(hist, lengths, mask):
            slot = jnp.clip(lengths, 0, B - 1)
            hits = (lax.iota(_I32, B)[:, None] == slot[None, :]) \
                & mask[None, :]
            return hist + jnp.sum(hits.astype(_I32), axis=1)

        out["hist_downtime"] = bump(mon["hist_downtime"], down_run, rec)
        out["hist_elect"] = bump(mon["hist_elect"], elect_run,
                                 rec & (elect_run > 0))
        out["down_ticks"] = mon["down_ticks"] + _s(~lead_c)
        cand = jnp.any((cur["role"] == CANDIDATE) & (cur["up"] != 0),
                       axis=0)
        out["grp_down_run"] = jnp.where(lead_c, 0, down_run + 1)
        out["grp_elect_run"] = jnp.where(lead_c, 0,
                                         elect_run + cand.astype(_I32))

    if "grp_age" in mon:
        # §19 retirement predicate: latch the group's age at the first
        # tick any arm fires — violation / lifetime horizon / quiescence.
        # Sticky; the admission loop folds retired lanes back to
        # init_state between segments (api/fuzz.make_continuous_runner).
        age = mon["grp_age"] + 1
        v_any = jnp.any(V, axis=0)
        r_p, r_c = prev.get("rounds"), cur.get("rounds")
        if r_p is None or r_c is None:
            raise ValueError(
                "the §19 scheduler channel needs `rounds` in the step "
                "views (monitor_view/monitor_flat_view supply it; a fused "
                "snapshot set does not — run the farm on a full-state "
                "engine)")
        d_rounds = jnp.sum(r_c.astype(_I32) - r_p.astype(_I32), axis=0)
        d_fault = jnp.sum(
            ((prev["up"] != 0) != (cur["up"] != 0)).astype(_I32), axis=0)
        calm = jnp.where(lead_c & (d_rounds == 0) & (d_fault == 0),
                         mon["grp_calm"] + 1, 0)
        life, q = mon["grp_life"], mon["sched_quiesce"]
        done = v_any | ((life > 0) & (age >= life)) \
            | ((q > 0) & (calm >= q))
        out["grp_retire_age"] = jnp.where(
            done & (mon["grp_retire_age"] < 0), age, mon["grp_retire_age"])
        out["grp_age"], out["grp_calm"] = age, calm

    # First-violation latch: within the tick, lexicographic (group, inv)
    # via one masked min over key = group * N_INVARIANTS + inv; across
    # ticks the scan order makes the first latching tick earliest.
    key = (lax.broadcasted_iota(_I32, V.shape, 1) * N_INVARIANTS
           + lax.broadcasted_iota(_I32, V.shape, 0))
    k = jnp.min(jnp.where(V, key, _RING_BIG))
    newly = (mon["latch_tick"] < 0) & (vc > 0)
    out["latch_tick"] = jnp.where(newly, tick, mon["latch_tick"])
    out["latch_group"] = jnp.where(newly, k // N_INVARIANTS,
                                   mon["latch_group"])
    out["latch_inv"] = jnp.where(newly, k % N_INVARIANTS, mon["latch_inv"])

    # History ring: slot (tick // stride) % W; a window's first tick
    # resets the slot to the signal's identity before combining.
    stride = mon["ring_stride"]
    W = mon["ring_violations"].shape[0]
    hot = lax.iota(_I32, W) == (tick // stride) % W
    entering = (tick % stride) == 0
    fr = jnp.max(cur["commit"].astype(_I32), axis=0)   # (G,) group frontier
    leaders = _s((cur["role"] == LEADER) & (cur["up"] != 0))
    if cur.get("vq_due") is not None:
        infl = _s(cur["vq_due"] >= 0) + _s(cur["aq_due"] >= 0)
    else:
        infl = jnp.zeros((), _I32)

    def ring(name, val, combine, ident):
        r = mon[f"ring_{name}"]
        base = jnp.where(entering, jnp.full_like(r, ident), r)
        out[f"ring_{name}"] = jnp.where(hot, combine(base, val), r)

    ring("commit_min", jnp.min(fr), jnp.minimum, _RING_BIG)
    ring("commit_max", jnp.max(fr), jnp.maximum, -1)
    ring("leaders", leaders, jnp.maximum, 0)
    ring("inflight_hw", infl, jnp.maximum, 0)
    ring("violations", vc, jnp.add, 0)

    if "series_data" in mon or "ev_kind" in mon:
        # §21 ops plane: shared per-tick reductions (SEMANTICS.md §21).
        # Same bit-neutrality contract as everything above — pre/post
        # state-transition reads only, phase_body untouched.
        prev_up = prev["up"] != 0
        cur_up = cur["up"] != 0
        lead_p = (prev["role"] == LEADER) & prev_up
        lead_c = (cur["role"] == LEADER) & cur_up
        new_lead = lead_c & ~lead_p                       # (N, G)
        led_p = jnp.any(lead_p, axis=0)                   # (G,)
        led_c = jnp.any(lead_c, axis=0)
        r_p, r_c = prev.get("rounds"), cur.get("rounds")
        if r_p is None or r_c is None:
            raise ValueError(
                "the §21 ops-plane channels need `rounds` in the step "
                "views (monitor_view/monitor_flat_view supply it; a "
                "monitor-only fused snapshot set does not — fuse with "
                "telemetry=True, whose snapshot set includes rounds)")
        d_rounds = jnp.sum(r_c.astype(_I32) - r_p.astype(_I32), axis=0)
        d_fault = jnp.sum((prev_up != cur_up).astype(_I32), axis=0)
        d_commit = jnp.maximum(
            cur["commit"].astype(_I32) - prev["commit"].astype(_I32), 0)
        v_grp = jnp.any(V, axis=0)                        # (G,)
        si_c = cur.get("snap_index")
        if si_c is not None:
            # The recorder's fold/install classifier, verbatim (see
            # telemetry_step_arrays) — the series columns must equal the
            # counter deltas bit-for-bit.
            restarted = cur_up & ~prev_up
            si_cc = si_c.astype(_I32)
            si_p = jnp.where(restarted, 0, prev["snap_index"].astype(_I32))
            li_p = jnp.where(restarted, 0,
                             prev["last_index"].astype(_I32))
            s_adv = si_cc > si_p
            s_inst = (s_adv & (si_cc > li_p)
                      & (si_cc <= cur["last_index"].astype(_I32)))
            s_fold = s_adv & ~s_inst
        else:
            zf = jnp.zeros(lead_c.shape, bool)
            si_cc, s_fold, s_inst = None, zf, zf
        cap_c, cap_p = cur.get("cap_ov"), prev.get("cap_ov")
        cap_new = ((cap_c != 0) & ~(cap_p != 0) if cap_c is not None
                   else jnp.zeros(lead_c.shape, bool))

    if "series_data" in mon:
        # §21 multi-channel series ring: one (K,) value vector per tick,
        # folded into the hot window with the per-channel combine from
        # the per-channel identity at window entry (the history-ring
        # idiom, vectorized over channels).
        if srv_prev is not None:
            d_hc = (srv_cur["hist_commit"].astype(_I32)
                    - srv_prev["hist_commit"].astype(_I32))
            d_hr = (srv_cur["hist_read"].astype(_I32)
                    - srv_prev["hist_read"].astype(_I32))
            bins_i = lax.iota(_I32, d_hc.shape[0])
            srv_vals = {
                "srv_applied": srv_cur["applied_total"].astype(_I32)
                - srv_prev["applied_total"].astype(_I32),
                "srv_reads": srv_cur["reads_ok"].astype(_I32)
                - srv_prev["reads_ok"].astype(_I32),
                "srv_commit_lat_sum": jnp.sum(d_hc * bins_i),
                "srv_read_lat_sum": jnp.sum(d_hr * bins_i),
                "srv_commit_lat_max": jnp.max(
                    jnp.where(d_hc > 0, bins_i, -1)),
                "srv_read_lat_max": jnp.max(
                    jnp.where(d_hr > 0, bins_i, -1)),
                "srv_read_q_hw": jnp.max(
                    srv_cur["grp_read_q"].astype(_I32)),
                "srv_applied_frontier": jnp.max(
                    srv_cur["applied"].astype(_I32)),
            }
        else:
            srv_vals = None
        vals_by = {
            "elections": jnp.sum(d_rounds),
            "leader_changes": _s(new_lead),
            "commit_advances": jnp.sum(d_commit),
            "fault_events": jnp.sum(d_fault),
            "violations": vc,
            "snapshot_folds": _s(s_fold),
            "installsnap": _s(s_inst),
            "commit_max": jnp.max(fr),
            "leaders_hw": leaders,
            "down_groups_hw": _s(~led_c),
            "inflight_hw": infl,
            "commit_min": jnp.min(fr),
        }
        vals = []
        for name, comb, ident in SERIES_CHANNELS:
            if name.startswith("srv_"):
                v = (srv_vals[name] if srv_vals is not None
                     else jnp.asarray(0 if comb == "sum" else ident, _I32))
            else:
                v = vals_by[name]
            vals.append(jnp.asarray(v, _I32))
        vals = jnp.stack(vals)                            # (K,)
        idents = jnp.asarray([c[2] for c in SERIES_CHANNELS], _I32)
        sum_m = jnp.asarray([c[1] == "sum" for c in SERIES_CHANNELS])
        max_m = jnp.asarray([c[1] == "max" for c in SERIES_CHANNELS])
        sd = mon["series_data"]
        ss = mon["series_stride"]
        hot_s = (lax.iota(_I32, sd.shape[0])
                 == (tick // ss) % sd.shape[0])[:, None]  # (W, 1)
        base_s = jnp.where((tick % ss) == 0,
                           jnp.broadcast_to(idents, sd.shape), sd)
        comb_s = jnp.where(sum_m, base_s + vals,
                           jnp.where(max_m, jnp.maximum(base_s, vals),
                                     jnp.minimum(base_s, vals)))
        out["series_data"] = jnp.where(hot_s, comb_s, sd)

    if "ev_kind" in mon:
        # §21 event ring: per-tick candidate events in lexicographic
        # (kind, group) order — the latch's masked-min key, realized as a
        # cumsum ordinal so every same-tick event gets a distinct slot —
        # scattered at cursor ev_count; rows past capacity drop into the
        # loud events_dropped counter.
        E = mon["ev_kind"].shape[0]
        G_ = fr.shape[0]
        arg0 = jnp.zeros((G_,), _I32)
        node_i = lax.broadcasted_iota(_I32, lead_c.shape, 0)
        big = jnp.asarray(_RING_BIG, _I32)
        masks, args = [], []

        def ev(mask, arg):  # order MUST follow EVENT_KINDS
            masks.append(mask)
            args.append(arg.astype(_I32))

        grp_new_lead = jnp.any(new_lead, axis=0)
        ev(grp_new_lead,
           jnp.min(jnp.where(new_lead, node_i, big), axis=0))
        ev(d_rounds > 0, d_rounds)
        ev(led_c & ~led_p,
           jnp.max(jnp.where(lead_c, cur["term"].astype(_I32), -1),
                   axis=0))
        ev(jnp.any(s_fold, axis=0),
           jnp.max(jnp.where(s_fold, si_cc, 0), axis=0)
           if si_cc is not None else arg0)
        ev(jnp.any(s_inst, axis=0),
           jnp.max(jnp.where(s_inst, si_cc, 0), axis=0)
           if si_cc is not None else arg0)
        ev(jnp.any(cap_new, axis=0), jnp.sum(cap_new.astype(_I32), axis=0))
        if "grp_retire_age" in mon:
            newly_ret = ((out["grp_retire_age"] >= 0)
                         & (mon["grp_retire_age"] < 0))
            ev(newly_ret, jnp.maximum(out["grp_retire_age"], 0))
        else:
            ev(jnp.zeros((G_,), bool), arg0)
        inv_i = lax.broadcasted_iota(_I32, V.shape, 0)
        ev(v_grp, jnp.min(jnp.where(V, inv_i, big), axis=0))
        assert len(masks) == N_EVENT_KINDS

        fm = jnp.stack(masks).reshape(-1)                 # (KE * G,)
        fa = jnp.stack(args).reshape(-1)
        kid = lax.broadcasted_iota(
            _I32, (N_EVENT_KINDS, G_), 0).reshape(-1)
        gid = lax.broadcasted_iota(
            _I32, (N_EVENT_KINDS, G_), 1).reshape(-1)
        ordinal = jnp.cumsum(fm.astype(_I32))
        cnt = mon["ev_count"]
        # Unmasked rows (and rows past capacity) aim at index >= E, which
        # mode="drop" discards — the scatter form of the masked-min latch.
        dest = jnp.where(fm, cnt + ordinal - 1, E)
        tick_v = jnp.broadcast_to(tick, dest.shape)
        out["ev_kind"] = mon["ev_kind"].at[dest].set(kid, mode="drop")
        out["ev_tick"] = mon["ev_tick"].at[dest].set(tick_v, mode="drop")
        out["ev_grp"] = mon["ev_grp"].at[dest].set(gid, mode="drop")
        out["ev_arg"] = mon["ev_arg"].at[dest].set(fa, mode="drop")
        total = jnp.sum(fm.astype(_I32))
        written = jnp.minimum(cnt + total, E) - jnp.minimum(cnt, E)
        out["ev_count"] = cnt + total
        out["events_dropped"] = mon["events_dropped"] + (total - written)

    out["tick"] = tick + 1
    return out


def monitor_view(state) -> dict:
    """The monitor view of a RaftState (every RaftState-carrying runner).
    `rounds` rides opportunistically — only the per-group stress counters
    (PER_GROUP_KEYS) read it. §15 snapshot fields ride when present."""
    v = {k: getattr(state, k) for k in MONITOR_STATE_FIELDS}
    v["rounds"] = getattr(state, "rounds", None)
    for k in TELEMETRY_MAILBOX_FIELDS + MONITOR_COMPACT_FIELDS:
        v[k] = getattr(state, k, None)
    return v


def monitor_flat_view(flat: dict, n_nodes: int) -> dict:
    """The monitor view of the flat rank-2 kernel layout (logs (N*C, G) ->
    (N, C, G)) — the Pallas flat-carry runner's form."""
    N = n_nodes
    v = {}
    for k in MONITOR_STATE_FIELDS:
        a = flat[k]
        v[k] = a.reshape(N, -1, a.shape[-1]) if k in ("log_term", "log_cmd") \
            else a
    v["rounds"] = flat.get("rounds")  # per-group counters only (see monitor_view)
    for k in TELEMETRY_MAILBOX_FIELDS:
        a = flat.get(k)
        v[k] = a.reshape(N, N, -1) if a is not None else None
    for k in MONITOR_COMPACT_FIELDS:
        v[k] = flat.get(k)
    return v


def monitor_step(prev_state, cur_state, mon: Dict[str, jax.Array],
                 srv_prev: Optional[dict] = None,
                 srv_cur: Optional[dict] = None) -> Dict[str, jax.Array]:
    """monitor_step_arrays over two RaftStates (one tick apart). The
    optional serving-carry pair (§20, one tick apart) feeds the §21
    srv_* series columns."""
    return monitor_step_arrays(monitor_view(prev_state),
                               monitor_view(cur_state), mon,
                               srv_prev=srv_prev, srv_cur=srv_cur)


def monitor_finalize(mon: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
    """End-of-run form: the (G,)-wide taint masks reduce to group counts
    (the coverage figure) so the result is O(W) small and shards/replicates
    trivially out of jit/shard_map. Idempotent."""
    if "taint_restart" not in mon:
        return dict(mon)
    out = {k: v for k, v in mon.items()
           if k not in ("taint_restart", "taint_unsafe")}
    out["taint_restart_groups"] = _s(mon["taint_restart"])
    out["taint_unsafe_groups"] = _s(mon["taint_unsafe"])
    return out


def universe_stats(mon: Dict[str, jax.Array]) -> dict:
    """Host materialization of the per-group (universe) channels of a RAW
    (un-finalized) per-group monitor carry: the PER_GROUP_KEYS counters
    plus the per-group taint masks — the farm's ranking/coverage input
    (api/fuzz.py). One batched device_get; arrays come back as numpy."""
    import numpy as np

    keys = [k for k in PER_GROUP_KEYS if k in mon]
    host = jax.device_get({k: mon[k] for k in keys + [
        k for k in ("taint_restart", "taint_unsafe") if k in mon]})
    return {k: np.asarray(v) for k, v in host.items()}


def sched_stats(mon: Dict[str, jax.Array]) -> dict:
    """Host materialization of the §19 scheduler/timing channels of a RAW
    carry (TIMING_KEYS + SCHED_KEYS, whichever are present) — the
    admission loop's per-segment readback (api/fuzz.continuous_farm). One
    batched device_get; arrays come back as numpy."""
    import numpy as np

    keys = [k for k in TIMING_KEYS + SCHED_KEYS if k in mon]
    host = jax.device_get({k: mon[k] for k in keys})
    return {k: np.asarray(v) for k, v in host.items()}


def monitor_scalars(mon: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
    """The monitor as FLAT () int32 scalars under the bench reporting
    prefix (inv_*) — the form that rides bench.measure's stats dicts and
    the deep runners' reduction dicts ({k: int(v)} materialization). Ring
    slots never written hold each signal's identity, so whole-ring
    aggregates need no used-window mask."""
    fin = monitor_finalize(mon)
    return {
        "inv_violations": fin["viol_total"],
        "inv_latch_tick": fin["latch_tick"],
        "inv_latch_group": fin["latch_group"],
        "inv_latch_inv": fin["latch_inv"],
        "inv_taint_restart_groups": fin["taint_restart_groups"],
        "inv_taint_unsafe_groups": fin["taint_unsafe_groups"],
        "inv_ring_commit_lo": jnp.min(fin["ring_commit_min"]),
        "inv_ring_commit_hi": jnp.max(fin["ring_commit_max"]),
        "inv_ring_leaders_hw": jnp.max(fin["ring_leaders"]),
        "inv_ring_inflight_hw": jnp.max(fin["ring_inflight_hw"]),
    }


def status_from_scalars(stats: Optional[dict]) -> Optional[str]:
    """The compact per-leg inv_status string from monitor_scalars output
    (host ints): "clean", or "<invariant>@t<tick>/g<group>". None when the
    stats carry no monitor (leg ran monitor-off)."""
    if not stats or "inv_latch_tick" not in stats:
        return None
    t = int(stats["inv_latch_tick"])
    if t < 0:
        return "clean"
    name = INVARIANT_IDS[int(stats["inv_latch_inv"])]
    return f"{name}@t{t}/g{int(stats['inv_latch_group'])}"


def _series_window_order(ticks: int, stride: int, W: int) -> list:
    """Chronological slot order for a stride-W ring after `ticks` ticks —
    the summarize_monitor wrap decode, shared by the §21 series ring."""
    total_w = -(-ticks // stride) if ticks else 0
    if total_w <= W:
        return list(range(total_w))
    first = total_w % W
    return [(first + i) % W for i in range(W)]


def decode_series_host(host: dict) -> Optional[dict]:
    """Decode a host copy of a §21 series carry (series_data (W, K) +
    series_stride + tick) into a chronological time-series frame:
    {"stride", "names", "windows": [{channel: int}...]}. None when the
    carry ran series-off. Pure host math — call it on the ONE
    summarize_monitor device_get, never separately."""
    sd = host.get("series_data")
    if sd is None:
        return None
    ticks = int(host["tick"])
    stride = int(host["series_stride"])
    order = _series_window_order(ticks, stride, int(sd.shape[0]))
    return {
        "stride": stride,
        "names": list(SERIES_NAMES),
        "windows": [{name: int(sd[w][k])
                     for k, name in enumerate(SERIES_NAMES)}
                    for w in order],
    }


def decode_events_host(host: dict) -> Optional[dict]:
    """Decode a host copy of a §21 event carry (ev_* + ev_count +
    events_dropped) into {"events": [{kind, tick, group, arg}...],
    "count", "dropped"}. Events come back in write order (tick-major,
    kind-major within a tick). None when the carry ran events-off."""
    ek = host.get("ev_kind")
    if ek is None:
        return None
    n = min(int(host["ev_count"]), int(ek.shape[0]))
    return {
        "events": [{"kind": EVENT_KINDS[int(ek[i])],
                    "kind_id": int(ek[i]),
                    "tick": int(host["ev_tick"][i]),
                    "group": int(host["ev_grp"][i]),
                    "arg": int(host["ev_arg"][i])}
                   for i in range(n)],
        "count": int(host["ev_count"]),
        "dropped": int(host["events_dropped"]),
    }


def render_events(decoded: dict, group: Optional[int] = None) -> str:
    """The §21 event ring as the reference repo's per-node narrative
    (api/explain.py style): one `[t=....] g... KIND arg` line per event,
    optionally filtered to one group, with a loud trailer when the ring
    dropped."""
    ev = decoded["events"]
    if group is not None:
        ev = [e for e in ev if e["group"] == group]
    hdr = (f"# ops-plane event ring: {len(ev)} events"
           + (f" (group {group})" if group is not None else "")
           + (f", {decoded['dropped']} DROPPED (ring full)"
              if decoded["dropped"] else ""))
    verbs = {
        "leader_change": lambda e: f"n{e['arg']} BECOMES LEADER",
        "election_start": lambda e: f"{e['arg']} election round(s) START",
        "election_resolve": lambda e: f"leadership RESTORED at term "
                                      f"{e['arg']}",
        "snapshot_fold": lambda e: f"snapshot FOLD to index {e['arg']}",
        "installsnap": lambda e: f"InstallSnapshot DELIVERED to index "
                                 f"{e['arg']}",
        "cap_latch": lambda e: f"{e['arg']} node(s) LATCH capacity",
        "retire": lambda e: f"universe RETIRES at age {e['arg']}",
        "violation": lambda e: f"invariant VIOLATION "
                               f"({INVARIANT_IDS[e['arg']]}"
                               f")" if 0 <= e["arg"] < len(INVARIANT_IDS)
                               else f"invariant VIOLATION (#{e['arg']})",
    }
    lines = [hdr]
    # Hosts may append kinds the device ring never writes (e.g. the
    # farm's "admit" rows) — render them generically instead of raising.
    fallback = lambda e: f"{e['kind'].upper()} arg={e['arg']}"
    for e in ev:
        lines.append(f"[t={e['tick']:>5}] g{e['group']} "
                     f"{verbs.get(e['kind'], fallback)(e)}")
    return "\n".join(lines)


# The §21 channels/kinds an independent host pass can recompute from the
# differential (T, N, G) trace (role/term/commit/last_index/voted_for/
# rounds/up) + the pre-run state. The remaining columns read state the
# trace does not carry (mailbox in-flight, §15 snapshot fields, cap_ov,
# the serving carry, the §19 scheduler) — tests pin THOSE by running
# configs where they provably stay at identity, so the full frame is
# still exactly recomputed (tests/test_opsplane.py).
TRACE_SERIES_NAMES = ("elections", "leader_changes", "commit_advances",
                      "fault_events", "commit_max", "leaders_hw",
                      "down_groups_hw", "commit_min")
TRACE_EVENT_KINDS = ("leader_change", "election_start", "election_resolve")


def _trace_pairs(state0, trace):
    """Yield (prev, cur) numpy view dicts per tick from a pre-run state +
    a (T, N, G) trace — the §21 recompute helpers' shared walk."""
    import numpy as np

    fields = ("role", "up", "commit", "rounds", "term")
    tr = {k: np.asarray(trace[k]) for k in fields}
    prev = {k: np.asarray(getattr(state0, k)) for k in fields}
    for t in range(tr["role"].shape[0]):
        cur = {k: tr[k][t] for k in fields}
        yield t, prev, cur
        prev = cur


def series_from_trace(state0, trace, windows: int, stride: int) -> dict:
    """Independent numpy recomputation of the trace-derivable §21 series
    columns (TRACE_SERIES_NAMES) from the pre-run state + a (T, N, G)
    trace — same fold, same wrap, same decode order as the device ring.
    Returns a decode_series_host-shaped frame restricted to those
    columns."""
    import numpy as np

    idents = {c[0]: c[2] for c in SERIES_CHANNELS}
    combs = {c[0]: c[1] for c in SERIES_CHANNELS}
    W = int(windows)
    sd = {n: np.full((W,), idents[n], np.int64) for n in TRACE_SERIES_NAMES}
    T = 0
    for t, prev, cur in _trace_pairs(state0, trace):
        T = t + 1
        p_up = prev["up"] != 0
        c_up = cur["up"] != 0
        lead_p = (prev["role"] == LEADER) & p_up
        lead_c = (cur["role"] == LEADER) & c_up
        fr = np.max(cur["commit"].astype(np.int64), axis=0)
        vals = {
            "elections": int(np.sum(cur["rounds"].astype(np.int64)
                                    - prev["rounds"].astype(np.int64))),
            "leader_changes": int(np.sum(lead_c & ~lead_p)),
            "commit_advances": int(np.sum(np.maximum(
                cur["commit"].astype(np.int64)
                - prev["commit"].astype(np.int64), 0))),
            "fault_events": int(np.sum(p_up != c_up)),
            "commit_max": int(np.max(fr)),
            "leaders_hw": int(np.sum(lead_c)),
            "down_groups_hw": int(np.sum(~np.any(lead_c, axis=0))),
            "commit_min": int(np.min(fr)),
        }
        slot = (t // stride) % W
        if t % stride == 0:
            for n in TRACE_SERIES_NAMES:
                sd[n][slot] = idents[n]
        for n in TRACE_SERIES_NAMES:
            if combs[n] == "sum":
                sd[n][slot] += vals[n]
            elif combs[n] == "max":
                sd[n][slot] = max(sd[n][slot], vals[n])
            else:
                sd[n][slot] = min(sd[n][slot], vals[n])
    order = _series_window_order(T, stride, W)
    return {
        "stride": int(stride),
        "names": list(TRACE_SERIES_NAMES),
        "windows": [{n: int(sd[n][w]) for n in TRACE_SERIES_NAMES}
                    for w in order],
    }


def events_from_trace(state0, trace, capacity: int) -> dict:
    """Independent numpy recomputation of the trace-derivable §21 event
    kinds (TRACE_EVENT_KINDS) from the pre-run state + a (T, N, G) trace
    — same per-tick kind-major/group-major order, same cursor/drop
    accounting as the device ring (over these kinds). Returns a
    decode_events_host-shaped dict."""
    import numpy as np

    cap = int(capacity)
    events, count = [], 0
    for t, prev, cur in _trace_pairs(state0, trace):
        p_up = prev["up"] != 0
        c_up = cur["up"] != 0
        lead_p = (prev["role"] == LEADER) & p_up
        lead_c = (cur["role"] == LEADER) & c_up
        new_lead = lead_c & ~lead_p
        led_p = np.any(lead_p, axis=0)
        led_c = np.any(lead_c, axis=0)
        d_rounds = np.sum(cur["rounds"].astype(np.int64)
                          - prev["rounds"].astype(np.int64), axis=0)
        G = lead_c.shape[1]
        node_i = np.arange(lead_c.shape[0])[:, None]
        big = np.iinfo(np.int32).max
        per_kind = {
            "leader_change": (np.any(new_lead, axis=0),
                              np.min(np.where(new_lead, node_i, big),
                                     axis=0)),
            "election_start": (d_rounds > 0, d_rounds),
            "election_resolve": (led_c & ~led_p,
                                 np.max(np.where(
                                     lead_c,
                                     cur["term"].astype(np.int64), -1),
                                     axis=0)),
        }
        for kind in TRACE_EVENT_KINDS:
            mask, arg = per_kind[kind]
            for g in range(G):
                if mask[g]:
                    if count < cap:
                        events.append({"kind": kind,
                                       "kind_id": EVENT_KINDS.index(kind),
                                       "tick": t, "group": g,
                                       "arg": int(arg[g])})
                    count += 1
    return {"events": events, "count": count,
            "dropped": max(0, count - cap)}


def summarize_monitor(mon: Dict[str, jax.Array]) -> dict:
    """Host materialization of a monitor carry (finalized or not) — ONE
    batched device_get. Returns inv_status, the latch, per-invariant
    counts, taint coverage, and the history ring decoded into
    chronological windows (wrap-around handled: long runs keep the LAST
    W windows). When the carry ran with the §21 ops plane, also the
    decoded series frame + event list — same single device_get."""
    host = jax.device_get(monitor_finalize(mon))
    ticks = int(host["tick"])
    stride = int(host["ring_stride"])
    W = len(host["ring_violations"])
    total_w = -(-ticks // stride) if ticks else 0
    if total_w <= W:
        order = list(range(total_w))
    else:
        first = total_w % W
        order = [(first + i) % W for i in range(W)]
    windows = [{sig: int(host[f"ring_{sig}"][w]) for sig in RING_SIGNALS}
               for w in order]
    lt = int(host["latch_tick"])
    latch = None if lt < 0 else {
        "tick": lt,
        "group": int(host["latch_group"]),
        "invariant_id": int(host["latch_inv"]),
        "invariant": INVARIANT_IDS[int(host["latch_inv"])],
    }
    status = "clean" if latch is None else (
        f"{latch['invariant']}@t{latch['tick']}/g{latch['group']}")
    out = {
        "inv_status": status,
        "latch": latch,
        "ticks": ticks,
        "violations": int(host["viol_total"]),
        "viol_by_inv": {name: int(host["viol_by_inv"][i])
                        for i, name in enumerate(INVARIANT_IDS)},
        "taint_restart_groups": int(host["taint_restart_groups"]),
        "taint_unsafe_groups": int(host["taint_unsafe_groups"]),
        "ring_stride": stride,
        "ring": windows,
    }
    series = decode_series_host(host)
    if series is not None:
        out["series"] = series
    events = decode_events_host(host)
    if events is not None:
        out["events"] = events["events"]
        out["events_count"] = events["count"]
        out["events_dropped"] = events["dropped"]
    return out


# ---------------------------------------------------------------------------
# Profiler scopes.

# The phase scope names, identical to opcount.phase_body_chain_depth
# (by_phase=True) attribution keys — a Perfetto trace groups ops under
# raft/<name> and the chain-depth model reports depth deltas under <name>,
# so the two line up column for column. "F0" covers the phase-F fault pass
# plus phase 0 (the same cut-0 boundary the attribution uses).
PHASE_SCOPES = ("F0", "p1", "p2", "p3", "p4", "p5")
SCOPE_PREFIX = "raft"


class PhaseScopes:
    """Sequential jax.named_scope manager for phase_body's LINEAR phase
    lattice: enter(name) closes the previous phase's scope and opens
    raft/<name>, so the 2000-line lattice gets phase-named HLO metadata
    without restructuring it into nested with-blocks. close() must run
    before every return (including the cut-truncated early returns).
    Trace-time metadata only — op names, never ops."""

    def __init__(self, prefix: str = SCOPE_PREFIX):
        self._prefix = prefix
        self._cm = None

    def enter(self, name: str) -> None:
        self.close()
        self._cm = jax.named_scope(f"{self._prefix}/{name}")
        self._cm.__enter__()

    def close(self) -> None:
        if self._cm is not None:
            self._cm.__exit__(None, None, None)
            self._cm = None


def engine_scope(name: str):
    """named_scope tagging one engine's tick ops (raft/engine/<name>) —
    names: xla, pallas, pallas-fused, xla-fcache, shardmap-xla,
    shardmap-pallas, shardmap-pallas-fused, shardmap-fcache."""
    return jax.named_scope(f"{SCOPE_PREFIX}/engine/{name}")


@contextlib.contextmanager
def trace_span(name: str):
    """Host-side jax.profiler.TraceAnnotation for run-level regions (no-op
    when the profiler is unavailable). Use around whole dispatches, not
    inside jit — in-trace regions come from PhaseScopes/engine_scope."""
    try:
        ann = jax.profiler.TraceAnnotation(name)
    except Exception:  # profiler backend absent (some CPU wheels)
        yield
        return
    with ann:
        yield
