"""Whole-simulation checkpoint / bit-exact resume.

The reference persists nothing — a restarted node rejoins at term 0 with an empty log
(reference RaftServer.kt:35-48); the Raft paper's "persistent state" requirement is
simply unimplemented. The TPU rebuild gets persistence *for free* at a stronger grain:
the entire simulation (all groups x nodes) is a pytree of arrays, so a checkpoint is a
single atomic array dump and resume is bit-exact — the RNG is counted threefry keyed by
on-state counters (utils/rng.py), so a resumed run replays the exact draw sequence the
uninterrupted run would have made.

Format: one .npz file holding every RaftState field plus a JSON header with the
RaftConfig (the config is part of the semantics — el_lo/el_hi etc. feed the counted
draws — so restoring under a different config is refused unless forced). Orbax is
available in the image but adds nothing here: the state is a flat dict of dense arrays
and .npz keeps the artifact a single portable file.
"""

from __future__ import annotations

import dataclasses
import json
import os
import tempfile
from typing import Optional, Tuple

import jax
import numpy as np

from raft_kotlin_tpu.models.state import RaftState
from raft_kotlin_tpu.utils.config import RaftConfig

_HEADER_KEY = "__raft_config_json__"
_EXTRA_KEY = "__raft_extra_json__"
_VERSION_KEY = "__raft_ckpt_version__"
_VERSION = 3  # v2: +up/+link_up fault-model fields; v3: groups-minor array layout


def save(path: str, state: RaftState, cfg: RaftConfig, extra: Optional[dict] = None) -> None:
    """Atomically write `state` (+ config header) to `path` (.npz).

    Sharded arrays are gathered to host first (np.asarray on a fully-addressable
    array concatenates its shards); multi-host checkpointing of non-addressable
    arrays should gather via jax.device_get on a replicated view first.
    """
    arrays = {
        f.name: np.asarray(jax.device_get(getattr(state, f.name)))
        for f in dataclasses.fields(state)
    }
    arrays[_HEADER_KEY] = np.frombuffer(
        json.dumps(dataclasses.asdict(cfg)).encode(), dtype=np.uint8
    )
    arrays[_EXTRA_KEY] = np.frombuffer(
        json.dumps(extra or {}).encode(), dtype=np.uint8
    )
    arrays[_VERSION_KEY] = np.asarray(_VERSION, dtype=np.int32)
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".npz.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez_compressed(f, **arrays)
        os.replace(tmp, path)  # atomic publish
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def load(
    path: str,
    expect_cfg: Optional[RaftConfig] = None,
    sharding=None,
) -> Tuple[RaftState, RaftConfig]:
    """Load a checkpoint. Returns (state, cfg-as-saved).

    If `expect_cfg` is given, any mismatch in semantics-bearing fields raises (the
    counted RNG makes config part of the trace). If `sharding` is given (a
    RaftState-shaped pytree of shardings, e.g. from parallel.mesh.state_sharding),
    each array is placed with that sharding; otherwise arrays land on the default
    device.
    """
    state, cfg, _ = _load_impl(path, expect_cfg, sharding)
    return state, cfg


def load_with_extra(
    path: str,
    expect_cfg: Optional[RaftConfig] = None,
    sharding=None,
) -> Tuple[RaftState, RaftConfig, dict]:
    """As load(), but also returns the extra dict passed to save()."""
    return _load_impl(path, expect_cfg, sharding)


def _load_impl(path, expect_cfg, sharding):
    with np.load(path) as z:
        version = int(z[_VERSION_KEY])
        if version not in (1, 2, _VERSION):
            raise ValueError(
                f"checkpoint version {version} not supported (can load 1-{_VERSION})")
        cfg_dict = json.loads(bytes(z[_HEADER_KEY].tobytes()).decode())
        extra = (
            json.loads(bytes(z[_EXTRA_KEY].tobytes()).decode())
            if _EXTRA_KEY in z
            else {}
        )
        arrays = {
            f.name: z[f.name]
            for f in dataclasses.fields(RaftState)
            if f.name in z
        }
    if version < 3:
        # v1/v2 stored groups-MAJOR arrays ((G, N), (G, N, N), (G, N, C)); v3 is
        # groups-minor (models/state.py). Pure relabeling — transpose losslessly.
        arrays = {
            k: (a if a.ndim == 0 else a.T if a.ndim == 2 else a.transpose(1, 2, 0))
            for k, a in arrays.items()
        }
    if version == 1:
        # v1 also predates the fault-model fields; their boot values (everything
        # healthy, matching init_state) are the only state a v1 run can have been in.
        N, G = arrays["term"].shape
        arrays.setdefault("up", np.ones((N, G), dtype=bool))
        arrays.setdefault("link_up", np.ones((N, N, G), dtype=bool))
    cfg = RaftConfig(**cfg_dict)
    if expect_cfg is not None and expect_cfg != cfg:
        raise ValueError(
            f"checkpoint config mismatch:\n saved   {cfg}\n expected {expect_cfg}"
        )
    if sharding is not None:
        state = RaftState(
            **{
                name: jax.device_put(a, getattr(sharding, name))
                for name, a in arrays.items()
            }
        )
    else:
        state = RaftState(**{name: jax.device_put(a) for name, a in arrays.items()})
    return state, cfg, extra
