"""Whole-simulation checkpoint / bit-exact resume.

The reference persists nothing — a restarted node rejoins at term 0 with an empty log
(reference RaftServer.kt:35-48); the Raft paper's "persistent state" requirement is
simply unimplemented. The TPU rebuild gets persistence *for free* at a stronger grain:
the entire simulation (all groups x nodes) is a pytree of arrays, so a checkpoint is a
single atomic array dump and resume is bit-exact — the RNG is counted threefry keyed by
on-state counters (utils/rng.py), so a resumed run replays the exact draw sequence the
uninterrupted run would have made.

Format: one .npz file holding every RaftState field plus a JSON header with the
RaftConfig (the config is part of the semantics — el_lo/el_hi etc. feed the counted
draws — so restoring under a different config is refused unless forced). Orbax is
available in the image but adds nothing here: the state is a flat dict of dense arrays
and .npz keeps the artifact a single portable file.

Layout normalization (ISSUE 11): checkpoints always STORE the wide
representation, whatever layout the run carried — save()/save_sharded()
accept a PackedRaftState and unpack it (after checking its width-overflow
latch), and load()/load_sharded() re-pack on request (`layout="packed"`).
A packed run can therefore resume any unpacked checkpoint and vice versa;
the on-disk format is layout-independent and needed no version bump
(pack/unpack is lossless by the SEMANTICS.md §14 roundtrip contract).
"""

from __future__ import annotations

import dataclasses
import json
import os
import tempfile
from typing import Optional, Tuple

import jax
import numpy as np

from raft_kotlin_tpu.models.state import RaftState
from raft_kotlin_tpu.utils.config import RaftConfig, config_from_dict

_HEADER_KEY = "__raft_config_json__"
_EXTRA_KEY = "__raft_extra_json__"
_VERSION_KEY = "__raft_ckpt_version__"
_VERSION = 9  # v2: +up/+link_up fault-model fields; v3: groups-minor array layout;
              # v4: optional §10 mailbox arrays (present iff cfg.uses_mailbox);
              # v5: +last_term lastLogTerm cache (derived from the log on load
              # of older checkpoints); v6: narrowed int16 storage for
              # structurally bounded fields (models/state.NARROW16) — loads of
              # ANY version cast to the canonical field dtypes (_canon_dtypes);
              # v7: +cap_ov capacity latch (zero-filled on older loads) and
              # optional §15 snapshot arrays (present iff cfg.uses_compaction
              # — snap_index is also the ring base, so a resume across a
              # truncation boundary restores the whole sliding window);
              # v8: §16 ring-window aware — the log planes are declared to be
              # the SAVED config's physical window (slot of position p is
              # p % phys_capacity), so a load may rebase the live logical
              # window [snap_index, phys_len) onto a DIFFERENT ring_capacity
              # (_resize_ring_window; expect_cfg may differ in ring_capacity
              # only). No array format change — v7 compaction checkpoints
              # (ring_capacity None, phys == C) resize-load the same way;
              # v9: optional §20 serving carry (ops/serving.SERVING_KEYS
              # under the __srv__ prefix — applied-KV planes, read
              # queue/lease fields, latency histograms), saved when the run
              # passes its carry to save()/save_sharded() and read back via
              # load_serving(). Older versions (and serving-off saves)
              # zero-fill for serving configs; None otherwise.


_SRV_PREFIX = "__srv__"
# Serving-carry arrays whose LAST axis is the groups axis (sharded saves
# slice these per shard; everything else — the tick/total scalars and the
# (B,) histograms — replicates into every shard file like the tick scalar).
_SRV_GROUPED = ("kv_val", "kv_ver", "applied", "apply_digest",
                "read_digest", "grp_read_q", "grp_read_age", "serve_viol")


def _serving_host(serving: dict) -> dict:
    """The carry as host numpy in canonical SERVING_KEYS order, validated
    complete (a partial carry must never become a checkpoint)."""
    from raft_kotlin_tpu.ops.serving import SERVING_KEYS

    host = jax.device_get(serving)
    missing = [k for k in SERVING_KEYS if k not in host]
    if missing:
        raise ValueError(f"serving carry is missing keys {missing}")
    return {k: np.asarray(host[k]) for k in SERVING_KEYS}


def _canon_dtypes(arrays: dict, cfg: RaftConfig) -> dict:
    """Cast loaded arrays to the canonical storage dtypes (v6 narrowing —
    models/state.field_dtype): every narrowed field's value range is
    structurally bounded, so the cast is lossless for any valid checkpoint."""
    from raft_kotlin_tpu.models.state import assert_narrow_bounds, field_dtype

    assert_narrow_bounds(cfg)  # an out-of-range cfg must fail loudly, not wrap
    out = {}
    for name, a in arrays.items():
        want = np.dtype(field_dtype(name, cfg)) if name != "tick" else a.dtype
        out[name] = a.astype(want) if a.dtype != want else a
    return out


def _derive_last_term(log_term, last_index):
    """last_term for v<5 checkpoints: log_term at physical slot last_index-1
    (0 when logically empty) — the §3 read phase 3 used to issue per tick."""
    li = last_index.astype(np.int64)
    idx = np.clip(li - 1, 0, log_term.shape[1] - 1)
    vals = np.take_along_axis(log_term, idx[:, None, :], axis=1)[:, 0, :]
    return np.where(li >= 1, vals, 0).astype(np.int32)


def _ring_only_mismatch(saved: RaftConfig, expect: RaftConfig) -> bool:
    """True when expect differs from the saved config ONLY in ring_capacity
    (§16) — the one semantics-free degree of freedom: logical positions are
    unbounded and the ring is pure storage, so the trace is unchanged."""
    return dataclasses.replace(saved, ring_capacity=expect.ring_capacity) == expect


def _resize_ring_window(arrays: dict, saved: RaftConfig,
                        target: RaftConfig) -> dict:
    """§16 resize-on-load: rebase the stored physical ring onto the target
    window. The stored slot of position p is p % C_phys_saved (the §15/§16
    ring map); the target slot is p % C_phys_target. Only the live window
    [snap_index, phys_len) transfers — every other row is dead (folded into
    the snapshot seat or never written). Loud-fails when any node's live
    window does not fit the target window: those rows exist nowhere else,
    so silently dropping them would corrupt the resume."""
    C_old, C_new = saved.phys_capacity, target.phys_capacity
    if C_old == C_new:
        return arrays
    assert saved.uses_compaction  # rings differ => both configs compact
    b = arrays["snap_index"].astype(np.int64)    # (N, G) window base
    live = arrays["phys_len"].astype(np.int64) - b
    hw = int(live.max()) if live.size else 0
    if hw > C_new:
        raise ValueError(
            f"checkpoint live log window ({hw} rows) does not fit the "
            f"target ring_capacity ({C_new}): resume at a window >= {hw} "
            f"or let compaction drain the backlog before saving")
    out = dict(arrays)
    for name in ("log_term", "log_cmd"):
        a = arrays[name]                         # (N, C_old, G)
        new = np.zeros((a.shape[0], C_new, a.shape[2]), dtype=a.dtype)
        for k in range(hw):                      # hw <= C_new, host-side
            p = b + k                            # (N, G) logical positions
            vals = np.take_along_axis(a, (p % C_old)[:, None, :], axis=1)
            dst = (p % C_new)[:, None, :]
            keep = np.take_along_axis(new, dst, axis=1)
            np.put_along_axis(
                new, dst, np.where((k < live)[:, None, :], vals, keep),
                axis=1)
        out[name] = new
    return out


def _normalize_wide(state, cfg: RaftConfig):
    """Accept either layout; return the wide RaftState (the only stored
    form). A packed state's width-overflow latch is checked first — a
    latched state holds wrapped values and must never become a
    checkpoint."""
    from raft_kotlin_tpu.models.state import (
        PackedRaftState, check_packed_ov, unpack_state)

    if isinstance(state, PackedRaftState):
        check_packed_ov(state.ov)
        return unpack_state(cfg, state)
    return state


def _apply_layout(state: RaftState, cfg: RaftConfig, layout: str):
    """Re-pack a loaded wide state when the resuming run carries
    layout="packed" (models/state.pack_state; loaded checkpoints are
    valid wide states, so the pack cannot latch — asserted anyway by the
    runner's own host check on first use)."""
    if layout == "wide":
        return state
    if layout != "packed":
        raise ValueError(f"unknown layout {layout!r}")
    from raft_kotlin_tpu.models.state import pack_state

    return pack_state(cfg, state)


def save(path: str, state: RaftState, cfg: RaftConfig,
         extra: Optional[dict] = None,
         serving: Optional[dict] = None) -> None:
    """Atomically write `state` (+ config header) to `path` (.npz).
    Accepts either layout; always stores wide (_normalize_wide).
    `serving` (v9) is a §20 serving carry to store alongside the state
    (ops/serving SERVING_KEYS, __srv__-prefixed); read back via
    load_serving().

    Sharded arrays are gathered to host first (np.asarray on a fully-addressable
    array concatenates its shards); multi-host checkpointing of non-addressable
    arrays should gather via jax.device_get on a replicated view first.
    """
    state = _normalize_wide(state, cfg)
    arrays = {
        f.name: np.asarray(jax.device_get(getattr(state, f.name)))
        for f in dataclasses.fields(state)
        if getattr(state, f.name) is not None  # §10 mailbox fields may be absent
    }
    if serving is not None:
        arrays.update({_SRV_PREFIX + k: v
                       for k, v in _serving_host(serving).items()})
    arrays[_HEADER_KEY] = np.frombuffer(
        json.dumps(dataclasses.asdict(cfg)).encode(), dtype=np.uint8
    )
    arrays[_EXTRA_KEY] = np.frombuffer(
        json.dumps(extra or {}).encode(), dtype=np.uint8
    )
    arrays[_VERSION_KEY] = np.asarray(_VERSION, dtype=np.int32)
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".npz.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez_compressed(f, **arrays)
        os.replace(tmp, path)  # atomic publish
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def load(
    path: str,
    expect_cfg: Optional[RaftConfig] = None,
    sharding=None,
    layout: str = "wide",
) -> Tuple[RaftState, RaftConfig]:
    """Load a checkpoint. Returns (state, cfg-as-saved).

    If `expect_cfg` is given, any mismatch in semantics-bearing fields raises (the
    counted RNG makes config part of the trace). If `sharding` is given (a
    RaftState-shaped pytree of shardings, e.g. from parallel.mesh.state_sharding),
    each array is placed with that sharding; otherwise arrays land on the default
    device. `layout="packed"` returns the state re-packed for a packed run
    (checkpoints store wide regardless — see the module docstring).
    """
    state, cfg, _ = _load_impl(path, expect_cfg, sharding)
    return _apply_layout(state, cfg, layout), cfg


def load_with_extra(
    path: str,
    expect_cfg: Optional[RaftConfig] = None,
    sharding=None,
    layout: str = "wide",
) -> Tuple[RaftState, RaftConfig, dict]:
    """As load(), but also returns the extra dict passed to save()."""
    state, cfg, extra = _load_impl(path, expect_cfg, sharding)
    return _apply_layout(state, cfg, layout), cfg, extra


def save_sharded(dirpath: str, state: RaftState, cfg: RaftConfig,
                 extra: Optional[dict] = None,
                 serving: Optional[dict] = None) -> None:
    """Checkpoint a SHARDED state without ever materializing a full array on the
    host: one .npz per device shard (each holding that device's slice of every
    field) plus a manifest. This is the config-5-scale path — `save()` gathers
    the whole pytree through one process, which at 100k-group x 10k-log scale is
    tens of GB; here each shard writes only its own groups-axis slice.

    Layout: dirpath/manifest.json + dirpath/shard_<k>.npz where k indexes the
    groups-axis slabs in ascending global offset. Restore with `load_sharded`
    under a mesh of ANY device count whose shard boundaries align (the common
    case: same total groups, any divisor count), or assemble unsharded.
    Accepts either state layout; always stores wide (_normalize_wide — the
    unpack is elementwise, so a sharded packed state unpacks shard-locally
    without gathering). `serving` (v9) stores the §20 carry: groups-axis
    planes sliced per shard, global scalars/histograms replicated into
    every shard file (the tick-scalar pattern); the carry is tiny, so the
    host materialization it takes is noise next to the log planes.
    """
    state = _normalize_wide(state, cfg)
    srv_host = _serving_host(serving) if serving is not None else None
    fields = [
        f.name for f in dataclasses.fields(state)
        if getattr(state, f.name) is not None
    ]
    # Shard boundaries from one representative groups-axis array (all state
    # arrays share the groups axis as their last dim; the tick scalar rides in
    # every shard file). Filenames are keyed by GLOBAL groups offset and the
    # manifest lists the GLOBAL shard map, so on a multi-host mesh each process
    # writes only its own shard files (disjoint names) and only process 0
    # writes the manifest — no clobbering.
    rep = state.term
    G = rep.shape[-1]

    def span(index):
        sl = index[-1]
        return int(sl.start or 0), int(sl.stop if sl.stop is not None else G)

    global_spans = sorted(
        {span(idx) for idx in rep.sharding.devices_indices_map(rep.shape).values()}
    )
    os.makedirs(dirpath, exist_ok=True)
    for sh in rep.addressable_shards:
        lo, hi = span(sh.index)
        arrays = {}
        for name in fields:
            arr = getattr(state, name)
            if arr.ndim == 0:
                arrays[name] = np.asarray(arr)
                continue
            local = [s for s in arr.addressable_shards
                     if span(s.index)[0] == lo]
            assert local, f"field {name} has no shard at groups offset {lo}"
            arrays[name] = np.asarray(local[0].data)
        if srv_host is not None:
            for k, a in srv_host.items():
                arrays[_SRV_PREFIX + k] = \
                    a[..., lo:hi] if k in _SRV_GROUPED else a
        fname = f"shard_g{lo:012d}.npz"
        tmp = os.path.join(dirpath, "." + fname + ".tmp")
        with open(tmp, "wb") as f:
            np.savez_compressed(f, **arrays)
        os.replace(tmp, os.path.join(dirpath, fname))
    if jax.process_index() == 0:
        manifest = {
            "version": _VERSION,
            "cfg": dataclasses.asdict(cfg),
            "extra": extra or {},
            "serving": srv_host is not None,
            "n_shards": len(global_spans),
            "offsets": [[lo, hi] for lo, hi in global_spans],
            "fields": fields,
            "shapes": {  # global shapes — restore needs no probe file reads
                name: list(getattr(state, name).shape) for name in fields
            },
        }
        tmp = os.path.join(dirpath, ".manifest.tmp")
        with open(tmp, "w") as f:
            json.dump(manifest, f)
        os.replace(tmp, os.path.join(dirpath, "manifest.json"))


def load_sharded(
    dirpath: str,
    mesh=None,
    expect_cfg: Optional[RaftConfig] = None,
    layout: str = "wide",
) -> Tuple[RaftState, RaftConfig]:
    """Restore a `save_sharded` checkpoint. With `mesh` (a jax.sharding.Mesh),
    each PROCESS opens only the shard files covering its own addressable
    devices' slices and device_puts only to those devices — on a multi-host
    mesh no host ever materializes (or even reads) the full groups axis.
    Without `mesh`, assembles unsharded arrays on the default device.
    `layout="packed"` re-packs for a packed run (elementwise — sharding
    is preserved shard-locally)."""
    with open(os.path.join(dirpath, "manifest.json")) as f:
        manifest = json.load(f)
    version = int(manifest.get("version", 0))
    if version not in (4, 5, 6, 7, 8, _VERSION):
        # The sharded layout first existed at v4 — fail loudly on
        # future/corrupt manifests, mirroring _load_impl's gate.
        raise ValueError(
            f"sharded checkpoint version {version} not supported "
            f"(this build reads 4-{_VERSION})")
    # config_from_dict, not RaftConfig(**...): a scenario config's nested
    # ScenarioSpec json-roundtrips as a plain dict and must be rebuilt —
    # the PR-8 fuzz-farm bank made scenario configs checkpointable state
    # holders, and a sharded farm resume must roundtrip them (r13).
    cfg = config_from_dict(manifest["cfg"])
    ring_to = None
    if expect_cfg is not None and expect_cfg != cfg:
        if not _ring_only_mismatch(cfg, expect_cfg):
            raise ValueError(
                f"checkpoint config mismatch:\n saved   {cfg}\n expected {expect_cfg}")
        # §16 resize-on-load, shard-locally: the ring rebase is per-(n, g)
        # along the C axis, so each shard file remaps its own groups slice
        # without gathering. The manifest's global log shapes switch to the
        # target window so device placement sizes the new arrays.
        ring_to = expect_cfg
        for name in ("log_term", "log_cmd"):
            manifest["shapes"][name][1] = expect_cfg.phys_capacity
    spans = manifest["offsets"]
    if version < 5 and "last_term" not in manifest["fields"]:
        # v4 predates the lastLogTerm cache: derive per shard on read (each
        # shard file carries its own full (N, C, g_slice) log).
        manifest["fields"] = list(manifest["fields"]) + ["last_term"]
        manifest["shapes"]["last_term"] = manifest["shapes"]["term"]
    if version < 7 and "cap_ov" not in manifest["fields"]:
        # pre-§15 checkpoints: a clean latch, zero-filled per shard.
        manifest["fields"] = list(manifest["fields"]) + ["cap_ov"]
        manifest["shapes"]["cap_ov"] = manifest["shapes"]["term"]

    loaded: dict = {}

    def shard_file(k):
        # Lazy per-file cache: only files actually covering a local slice load.
        if k not in loaded:
            fname = f"shard_g{spans[k][0]:012d}.npz"
            with np.load(os.path.join(dirpath, fname)) as z:
                d = {name: z[name] for name in manifest["fields"] if name in z}
            if "last_term" not in d:
                d["last_term"] = _derive_last_term(
                    d["log_term"], d["last_index"])
            if "cap_ov" not in d:
                d["cap_ov"] = np.zeros(d["term"].shape, dtype=np.int16)
            d = _canon_dtypes(d, cfg)
            if ring_to is not None:
                d = _resize_ring_window(d, cfg, ring_to)
            loaded[k] = d
        return loaded[k]

    # The resumed run IS the target config when a ring rebase happened —
    # the returned cfg sizes its runner's arrays (shard_file keeps the
    # saved cfg: it is the source geometry of the rebase).
    cfg_out = ring_to if ring_to is not None else cfg

    if mesh is None:
        fields = {}
        for name in manifest["fields"]:
            parts = [shard_file(k)[name] for k in range(len(spans))]
            fields[name] = jax.device_put(
                parts[0] if parts[0].ndim == 0 else np.concatenate(parts, axis=-1))
        return _apply_layout(RaftState(**fields), cfg_out, layout), cfg_out

    from raft_kotlin_tpu.parallel.mesh import state_sharding

    sh = state_sharding(mesh, cfg_out)
    G = cfg.n_groups

    def device_slice(name, lo, hi):
        # Gather [lo, hi) of the groups axis from the covering shard files.
        parts = []
        for k, (off, end) in enumerate(spans):
            if end <= lo or off >= hi:
                continue
            a = shard_file(k)[name]
            parts.append(a[..., max(lo - off, 0): hi - off])
        return parts[0] if len(parts) == 1 else np.concatenate(parts, axis=-1)

    proc = jax.process_index()
    # Which manifest spans overlap THIS process's device slices (via any
    # groups-sharded field's device map) — the only shard files we may open.
    rep_shape = tuple(manifest["shapes"]["term"])
    local_ranges = [
        (int(idx[-1].start or 0),
         int(idx[-1].stop if idx[-1].stop is not None else G))
        for dev, idx in sh.term.devices_indices_map(rep_shape).items()
        if dev.process_index == proc
    ]
    local_span_ks = [
        k for k, (off, end) in enumerate(spans)
        if any(end > lo and off < hi for lo, hi in local_ranges)
    ]
    fields = {}
    for name in manifest["fields"]:
        target = getattr(sh, name)
        full_shape = tuple(manifest["shapes"][name])
        if not full_shape:  # scalar (the tick counter, in every shard file)
            # Assembled per ADDRESSABLE device: a device_put straight to the
            # mesh-wide (replicated) sharding would raise on a multi-process
            # mesh, where some of its devices belong to other processes.
            val = np.asarray(shard_file(local_span_ks[0])[name])
            singles = [
                jax.device_put(val, dev)
                for dev, _ in target.devices_indices_map(full_shape).items()
                if dev.process_index == proc
            ]
            fields[name] = jax.make_array_from_single_device_arrays(
                full_shape, target, singles)
            continue
        singles = []
        for dev, idx in target.devices_indices_map(full_shape).items():
            if dev.process_index != proc:
                continue  # non-addressable: that host supplies its own shards
            sl = idx[-1]
            lo = int(sl.start or 0)
            hi = int(sl.stop if sl.stop is not None else G)
            singles.append(jax.device_put(device_slice(name, lo, hi), dev))
        fields[name] = jax.make_array_from_single_device_arrays(
            full_shape, target, singles)
    return _apply_layout(RaftState(**fields), cfg_out, layout), cfg_out


def load_serving(path: str):
    """The §20 serving carry stored alongside a checkpoint (v9). `path` is
    a save() .npz file or a save_sharded() directory. Returns the carry as
    saved (int32 jax arrays keyed by SERVING_KEYS); a ZERO carry when the
    checkpoint predates v9 or was saved without one but its config serves
    (cfg.serve_slots > 0 — the zero-fill rule: the apply cursor restarts
    at 0 and refolds, which the digest fold makes bit-convergent); None
    for non-serving configs."""
    import jax.numpy as jnp

    from raft_kotlin_tpu.ops.serving import (
        SERVING_KEYS, serving_enabled, serving_zeros)

    if os.path.isdir(path):
        with open(os.path.join(path, "manifest.json")) as f:
            manifest = json.load(f)
        cfg = config_from_dict(manifest["cfg"])
        if not serving_enabled(cfg):
            return None
        if not manifest.get("serving", False):
            return serving_zeros(cfg.n_groups, cfg.serve_slots)
        spans = manifest["offsets"]
        shard = {}
        grouped: dict = {k: [] for k in _SRV_GROUPED}
        for k_idx, (lo, _hi) in enumerate(spans):
            fname = f"shard_g{lo:012d}.npz"
            with np.load(os.path.join(path, fname)) as z:
                for key in SERVING_KEYS:
                    a = z[_SRV_PREFIX + key]
                    if key in _SRV_GROUPED:
                        grouped[key].append(a)
                    elif k_idx == 0:  # replicated — any shard file's copy
                        shard[key] = a
        for key, parts in grouped.items():
            shard[key] = np.concatenate(parts, axis=-1)
        return {k: jnp.asarray(shard[k], jnp.int32) for k in SERVING_KEYS}

    with np.load(path) as z:
        cfg = config_from_dict(
            json.loads(bytes(z[_HEADER_KEY].tobytes()).decode()))
        if not serving_enabled(cfg):
            return None
        if _SRV_PREFIX + "tick" not in z:
            return serving_zeros(cfg.n_groups, cfg.serve_slots)
        return {k: jnp.asarray(z[_SRV_PREFIX + k], jnp.int32)
                for k in SERVING_KEYS}


def _load_impl(path, expect_cfg, sharding):
    with np.load(path) as z:
        version = int(z[_VERSION_KEY])
        if version not in (1, 2, 3, 4, 5, 6, 7, 8, _VERSION):
            raise ValueError(
                f"checkpoint version {version} not supported (can load 1-{_VERSION})")
        cfg_dict = json.loads(bytes(z[_HEADER_KEY].tobytes()).decode())
        extra = (
            json.loads(bytes(z[_EXTRA_KEY].tobytes()).decode())
            if _EXTRA_KEY in z
            else {}
        )
        arrays = {
            f.name: z[f.name]
            for f in dataclasses.fields(RaftState)
            if f.name in z
        }
    if version < 3:
        # v1/v2 stored groups-MAJOR arrays ((G, N), (G, N, N), (G, N, C)); v3 is
        # groups-minor (models/state.py). Pure relabeling — transpose losslessly.
        arrays = {
            k: (a if a.ndim == 0 else a.T if a.ndim == 2 else a.transpose(1, 2, 0))
            for k, a in arrays.items()
        }
    if version == 1:
        # v1 also predates the fault-model fields; their boot values (everything
        # healthy, matching init_state) are the only state a v1 run can have been in.
        N, G = arrays["term"].shape
        arrays.setdefault("up", np.ones((N, G), dtype=bool))
        arrays.setdefault("link_up", np.ones((N, N, G), dtype=bool))
    if version < 5 and "last_term" not in arrays:
        arrays["last_term"] = _derive_last_term(
            arrays["log_term"], arrays["last_index"])
    if version < 7 and "cap_ov" not in arrays:
        # v7 predates the §15 capacity latch: clean by assumption (pre-v7
        # configs had no latch to record).
        arrays["cap_ov"] = np.zeros(arrays["term"].shape, dtype=np.int16)
    cfg = config_from_dict(cfg_dict)  # rebuilds a nested ScenarioSpec too
    arrays = _canon_dtypes(arrays, cfg)
    from raft_kotlin_tpu.models.state import MAILBOX_FIELDS, SNAPSHOT_FIELDS

    missing = [
        f.name for f in dataclasses.fields(RaftState)
        if f.name not in arrays
        and (f.name not in MAILBOX_FIELDS or cfg.uses_mailbox)
        and (f.name not in SNAPSHOT_FIELDS or cfg.uses_compaction)
    ]
    if missing:
        raise ValueError(
            f"checkpoint {path!r} is corrupt/truncated: missing arrays {missing}"
        )
    if expect_cfg is not None and expect_cfg != cfg:
        if not _ring_only_mismatch(cfg, expect_cfg):
            raise ValueError(
                f"checkpoint config mismatch:\n saved   {cfg}\n expected {expect_cfg}"
            )
        # §16: ring_capacity is the one tolerated difference — rebase the
        # live window onto the requested physical ring and resume AS the
        # requested config (the returned cfg sizes the runner's arrays).
        arrays = _resize_ring_window(arrays, cfg, expect_cfg)
        cfg = expect_cfg
    if sharding is not None:
        state = RaftState(
            **{
                name: jax.device_put(a, getattr(sharding, name))
                for name, a in arrays.items()
            }
        )
    else:
        state = RaftState(**{name: jax.device_put(a) for name, a in arrays.items()})
    return state, cfg, extra
