"""Observability: on-device metric reductions, invariant checks, trace streaming.

The reference's only observability is stdout logging (kotlin-logging over slf4j,
reference RaftServer.kt:33,56,110,134-135 and a raw println at RaftServer.kt:134) plus
the HTTP `GET /` log dump (RaftServer.kt:84-86). Here observability is a first-class
subsystem designed for 100k concurrent groups: everything is computed ON DEVICE as O(1)
scalar reductions per tick (never materialize (G, N) arrays on the host), fetched at
low frequency, and streamed as JSONL.

Three pieces:
- `tick_metrics(prev, cur)` — pure, jittable: scalar reductions over a tick transition
  (leaders, elections started, commit throughput, safety telemetry).
- `check_invariants(prev, cur, cfg)` — pure, jittable: violation COUNTS for properties
  the SEMANTICS.md tick machine guarantees. This is the rebuild's "race detector": the
  reference has real data races (unsynchronized commitIndex/nextIndex/matchIndex,
  RaftServer.kt:112-167, @Volatile-only fields RaftServer.kt:35-42); the lockstep kernel
  makes races structurally impossible, and these checks prove the state machine stays
  inside its lattice. Any nonzero count is a framework bug, not a simulation outcome.
- `MetricsRecorder` — host-side JSONL streaming + optional jax.profiler wrapping.
"""

from __future__ import annotations

import contextlib
import json
import time
from typing import Dict, IO, Optional

import jax
import jax.numpy as jnp

from raft_kotlin_tpu.constants import ACTIVE, BACKOFF, CANDIDATE, LEADER
from raft_kotlin_tpu.models.state import RaftState
from raft_kotlin_tpu.utils.config import RaftConfig

_I32 = jnp.int32


def tick_metrics(prev: RaftState, cur: RaftState) -> Dict[str, jax.Array]:
    """Scalar on-device reductions for the transition prev -> cur (one tick apart).

    Keys (all () int32 unless noted):
    - leaders:            groups with >= 1 LIVE LEADER node (a §9-crashed node keeps
                          role=LEADER inert while up=False; it does not lead)
    - multi_leader:       groups with >= 2 live LEADER nodes (any terms)
    - split_leaders:      groups with two live leaders in the SAME term — classical
                          Raft's Election Safety violation; reachable in the
                          reference's semantics (quirks d/f/g), so it is telemetry,
                          not an error
    - elections:          nodes that entered a new vote round this tick
    - rounds_active:      live nodes currently in an ACTIVE vote round
    - candidates:         live nodes currently CANDIDATE
    - commit_advanced:    sum over (g, n) of commit increase (clipped at 0) — the
                          commit-throughput numerator
    - commit_total:       sum over groups of the max node commit
    - term_max:           max term anywhere
    - log_bytes_used:     total readable log slots (sum of last_index)
    """
    # State is groups-minor: role/term are (N, G); node axis = 0.
    is_leader = (cur.role == LEADER) & cur.up
    n_lead = jnp.sum(is_leader.astype(_I32), axis=0)  # (G,)

    # Same-term leader pairs, O(N^2) on the tiny node axis (the is_leader factors
    # already restrict the comparison to leader-leader pairs).
    N = cur.term.shape[0]
    t = cur.term
    same = (t[:, None, :] == t[None, :, :]) & is_leader[:, None, :] & is_leader[None, :, :]
    same = same & ~jnp.eye(N, dtype=bool)[:, :, None]
    split = jnp.any(same, axis=(0, 1))

    d_commit = jnp.maximum(cur.commit - prev.commit, 0)
    return {
        "tick": cur.tick,
        "leaders": jnp.sum((n_lead >= 1).astype(_I32)),
        "multi_leader": jnp.sum((n_lead >= 2).astype(_I32)),
        "split_leaders": jnp.sum(split.astype(_I32)),
        "elections": jnp.sum((cur.rounds - prev.rounds).astype(_I32)),
        # Like the leader metrics, activity metrics count LIVE nodes only: a §9
        # crash freezes role/round_state inert while up=False.
        "rounds_active": jnp.sum(((cur.round_state == ACTIVE) & cur.up).astype(_I32)),
        "candidates": jnp.sum(((cur.role == CANDIDATE) & cur.up).astype(_I32)),
        "commit_advanced": jnp.sum(d_commit.astype(_I32)),
        "commit_total": jnp.sum(jnp.max(cur.commit, axis=0).astype(_I32)),
        "term_max": jnp.max(cur.term),
        "log_bytes_used": jnp.sum(cur.last_index.astype(_I32)),
    }


def check_invariants(prev: RaftState, cur: RaftState, cfg: RaftConfig) -> Dict[str, jax.Array]:
    """Violation counts for properties the tick machine (SEMANTICS.md §5) guarantees.

    Nonzero => kernel bug. Checked:
    - term_monotone:     per-node term never decreases (every term write in §5/§6 is
                         either +=1 or adoption of a strictly higher term) — except
                         across a §9 restart, which wipes term to 0 (a node that came
                         up this tick is exempt)
    - log_window:        0 <= last_index <= phys_len, and the live window
                         phys_len - snap_index fits the physical ring
                         (snap_index taken as 0 without compaction —
                         SEMANTICS.md §3, §15/§16)
    - role_range:        role in {F, C, L}; round_state in {IDLE, BACKOFF, ACTIVE}
    - vote_accounting:   0 <= votes <= responses <= N, and responses ==
                         count(responded) for nodes in an ACTIVE round
    - rng_counters:      t_ctr/b_ctr nonnegative and nondecreasing
    - commit_in_window:  0 <= commit (commit may exceed last_index transiently per
                         quirk e semantics? no — commit is always min'd against
                         last_index when advanced, and last_index only shrinks via
                         truncation which does not touch commit... truncation CAN
                         strand commit > last_index, so only nonnegativity is owed)
    - int16_wrap:        (log_dtype="int16" runs only) values at or past the int16
                         write boundary: source terms >= 32767 (the next log_add of
                         that term narrows into wrap), stored log values pinned at
                         32767, and NEGATIVE stored values (log terms/commands are
                         nonnegative by construction in the int32 semantics, so a
                         negative stored entry proves a wrap already happened).
                         utils/config.py:28-34 documents the bound; this makes a
                         deep-log soak fail loudly instead of corrupting silently.

    Note commit monotonicity is deliberately NOT here: quirk e
    (reference RaftServer.kt:270-272) computes min(leaderCommit, last_index), which
    after a log truncation can legitimately LOWER a stale follower's commit.
    The Figure-3 safety invariants (election safety, log matching, leader
    completeness, state machine safety) live in utils/telemetry's
    invariant_matrix — the ONE source of truth shared by the on-device
    monitor carry and this host path; figure3_counts below is the
    host-side entry and make_instrumented_run(invariants=True) threads it
    per tick (quirk-taint masks carried across the scan, SEMANTICS.md §11),
    including the group-frontier commit-monotonicity form that IS a
    theorem of the quirk semantics.
    """
    N = cfg.n_nodes

    def cnt(bad) -> jax.Array:
        return jnp.sum(bad.astype(_I32))

    # responded is (N, N, G) [c-1, p-1, g]: count responses over the peer axis.
    resp_cnt = jnp.sum(cur.responded.astype(_I32), axis=1)
    in_round = cur.round_state == ACTIVE
    restarted = cur.up & ~prev.up
    extra = {}
    if cfg.log_dtype == "int16":
        lim = jnp.int32(2 ** 15 - 1)
        extra["int16_wrap"] = (
            cnt(cur.term >= lim)
            + cnt(cur.log_term.astype(_I32) < 0)
            + cnt(cur.log_cmd.astype(_I32) < 0)
            + cnt(cur.log_term.astype(_I32) == lim)
            + cnt(cur.log_cmd.astype(_I32) == lim)
        )
    return {
        **extra,
        "term_monotone": cnt((cur.term < prev.term) & ~restarted),
        # §3 bound without compaction; §15/§16: positions are unbounded
        # but the LIVE WINDOW phys_len - snap_index must fit the physical
        # ring (the log_add capacity clip guarantees it).
        "log_window": cnt(
            (cur.last_index < 0)
            | (cur.last_index > cur.phys_len)
            | ((cur.phys_len
                - (cur.snap_index if cfg.uses_compaction else 0))
               > cfg.phys_capacity)
        ),
        "role_range": cnt((cur.role < 0) | (cur.role > LEADER))
        + cnt((cur.round_state < 0) | (cur.round_state > ACTIVE)),
        "vote_accounting": cnt(
            (cur.votes < 0) | (cur.votes > cur.responses) | (cur.responses > N)
        )
        + cnt(in_round & (cur.responses != resp_cnt)),
        "rng_counters": cnt(cur.t_ctr < prev.t_ctr) + cnt(cur.b_ctr < prev.b_ctr),
        "commit_in_window": cnt(cur.commit < 0),
    }


def figure3_counts(prev: RaftState, cur: RaftState,
                   taint_restart: jax.Array, taint_unsafe: jax.Array):
    """Host-path Figure-3 verdicts for one transition: violation COUNTS per
    invariant plus the advanced sticky taint masks — a thin wrapper over
    utils/telemetry.invariant_matrix, which is the ONE definition the
    on-device monitor carry also runs (tests/test_invariants.py pins the
    two paths' latches equal differentially). Returns
    ({"fig3_<invariant>": () i32 count}, taint_restart', taint_unsafe')."""
    from raft_kotlin_tpu.utils import telemetry as telemetry_mod

    V, tr, tu = telemetry_mod.invariant_matrix(
        telemetry_mod.monitor_view(prev), telemetry_mod.monitor_view(cur),
        taint_restart, taint_unsafe)
    counts = jnp.sum(V.astype(_I32), axis=1)
    out = {f"fig3_{name}": counts[i]
           for i, name in enumerate(telemetry_mod.INVARIANT_IDS)}
    return out, tr, tu


def make_instrumented_run(
    cfg: RaftConfig,
    n_ticks: int,
    invariants: bool = False,
    impl: str = "auto",
    batched=None,
):
    """jitted run(state) -> (state, metrics) where metrics is a dict of (n_ticks,)
    arrays from `tick_metrics` (plus, when invariants=True — the debug
    mode — `check_invariants` counts AND the Figure-3 per-tick violation
    counts from `figure3_counts`, with the quirk-taint masks carried
    across the scan; ~free, but adds a few reductions per tick). impl as in
    Simulator: "xla", "pallas", or "auto" (ops/pallas_tick.choose_impl).
    `batched=False` forces the per-pair deep-log engine (ops/tick.make_tick —
    XLA:CPU compiles of the batched engine blow up on int16 deep configs, so
    CPU-bound instrumented runs of such configs pass this)."""
    from raft_kotlin_tpu.ops.tick import make_tick

    if impl == "auto":
        from raft_kotlin_tpu.ops.pallas_tick import choose_impl

        impl = choose_impl(cfg)
    if impl == "pallas":
        from raft_kotlin_tpu.ops.pallas_tick import make_pallas_tick

        tick_fn = make_pallas_tick(cfg)
    else:
        tick_fn = make_tick(cfg, batched=batched)
    from raft_kotlin_tpu.ops.tick import make_rng

    rng = make_rng(cfg)

    @jax.jit
    def run(st, rng):
        def body(carry, _):
            st, tr, tu = carry
            nxt = tick_fn(st, rng=rng)
            out = tick_metrics(st, nxt)
            if invariants:
                out.update({f"inv_{k}": v
                            for k, v in check_invariants(st, nxt, cfg).items()})
                fig3, tr, tu = figure3_counts(st, nxt, tr, tu)
                out.update({f"inv_{k}": v for k, v in fig3.items()})
            return (nxt, tr, tu), out

        z = jnp.zeros((cfg.n_groups,), dtype=bool)
        (end, _, _), ms = jax.lax.scan(body, (st, z, z), None,
                                       length=n_ticks)
        return end, ms

    # rng as a jit operand: the compiled program is seed-independent.
    return lambda st: run(st, rng)


class MetricsRecorder:
    """Streams per-window metric dicts to JSONL; one line per fetch window.

    Usage: run a chunk of ticks with `make_instrumented_run`, then
    `rec.record(metrics)` — record BUFFERS the device arrays and returns
    immediately, issuing NO device->host transfer (ISSUE 5 satellite: the
    old record() device_get'd every call, which at record-per-tick cadence
    was a per-tick device sync — unusable inside a 100k-group production
    loop). The stacked scan outputs stay on device until `flush()` /
    `summary()` / `close()`, which materialize EVERY pending window in one
    batched `jax.device_get` (the single transfer point — the laziness
    test counts calls to exactly that function) and only then write JSONL.
    """

    def __init__(self, path: Optional[str] = None,
                 autoflush_windows: int = 64):
        self._fh: Optional[IO[str]] = open(path, "a") if path else None
        self._t0 = time.time()
        self.windows: list[dict] = []
        self._pending: list = []  # [(device metrics pytree, wall_s)]
        # Bounded staleness: a crash mid-soak loses at most this many
        # buffered windows (one batched transfer per autoflush, amortized
        # — never per record()). <= 0 disables auto-flush entirely.
        self._autoflush = autoflush_windows

    def record(self, metrics: Dict[str, jax.Array]) -> None:
        """Buffer one window's metrics pytree — no transfer, no sync; the
        arrays may still be unfinished device computations. Every
        `autoflush_windows` buffered windows, one amortized flush() keeps
        the JSONL stream live and bounds crash loss."""
        self._pending.append((metrics, round(time.time() - self._t0, 3)))
        if 0 < self._autoflush <= len(self._pending):
            self.flush()

    def flush(self) -> None:
        """Materialize every pending window (ONE batched device_get) and
        stream the JSONL lines."""
        if not self._pending:
            return
        pending, self._pending = self._pending, []
        host_all = jax.device_get([m for m, _ in pending])
        for host, (_, wall) in zip(host_all, pending):
            window = {}
            for k, v in host.items():
                v = v.tolist() if hasattr(v, "tolist") else v
                if isinstance(v, list) and v:
                    window[k] = {"first": v[0], "last": v[-1],
                                 "sum": int(sum(v)),
                                 "max": int(max(v)), "n": len(v)}
                else:
                    window[k] = v
            window["wall_s"] = wall
            self.windows.append(window)
            if self._fh:
                self._fh.write(json.dumps(window) + "\n")
        if self._fh:
            self._fh.flush()

    def summary(self) -> dict:
        self.flush()
        out: dict = {"windows": len(self.windows)}
        for w in self.windows:
            for k, v in w.items():
                if isinstance(v, dict) and "sum" in v:
                    agg = out.setdefault(k, {"sum": 0, "max": 0, "n": 0})
                    agg["sum"] += v["sum"]
                    agg["max"] = max(agg["max"], v["max"])
                    agg["n"] += v["n"]
        return out

    def close(self) -> None:
        self.flush()
        if self._fh:
            self._fh.close()
            self._fh = None


@contextlib.contextmanager
def profile(logdir: str):
    """jax.profiler trace around a block — TensorBoard-compatible XLA traces, the
    rebuild's answer to the reference's printf profiling."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
