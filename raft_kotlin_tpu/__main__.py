"""CLI bootstrap — the reference's `raftInstance`/`main` equivalent.

The reference boots one node per process, hard-coding ids/ports/cluster shape in
`main` (reference RaftServer.kt:290-310); a 3-node cluster means editing `main` and
running 3 JVMs. Here one process hosts the whole simulation (all groups x nodes) and
`serve` exposes the reference's HTTP verbs over it:

    python -m raft_kotlin_tpu serve --groups 4 --nodes 3 --port 7000 --tick-hz 10
    python -m raft_kotlin_tpu run --groups 1024 --nodes 5 --ticks 500
    python -m raft_kotlin_tpu bench

tick-hz 10 reproduces the reference's real-time pacing (1 tick = 100 ms,
SEMANTICS.md §1); tick-hz 0 gives a manually-stepped clock via GET /step/{k}.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _add_cfg_args(p: argparse.ArgumentParser) -> None:
    p.add_argument("--groups", type=int, default=1)
    p.add_argument("--nodes", type=int, default=3)
    p.add_argument("--log-capacity", type=int, default=64)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--p-drop", type=float, default=0.0)
    p.add_argument("--p-crash", type=float, default=0.0)
    p.add_argument("--p-restart", type=float, default=0.0)
    p.add_argument("--p-link-fail", type=float, default=0.0)
    p.add_argument("--p-link-heal", type=float, default=0.0)
    p.add_argument("--cmd-period", type=int, default=0)
    p.add_argument("--stress", type=int, default=1,
                   help="divide all pacing constants by this factor")
    p.add_argument("--impl", choices=["auto", "xla", "pallas"], default="auto",
                   help="tick backend (pallas = the TPU megakernel)")


def _cfg_from(args) -> "RaftConfig":
    from raft_kotlin_tpu.utils.config import RaftConfig

    cfg = RaftConfig(
        n_groups=args.groups,
        n_nodes=args.nodes,
        log_capacity=args.log_capacity,
        seed=args.seed,
        p_drop=args.p_drop,
        p_crash=args.p_crash,
        p_restart=args.p_restart,
        p_link_fail=args.p_link_fail,
        p_link_heal=args.p_link_heal,
        cmd_period=args.cmd_period,
    )
    return cfg.stressed(args.stress) if args.stress > 1 else cfg


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="raft_kotlin_tpu")
    sub = ap.add_subparsers(dest="command", required=True)

    serve = sub.add_parser("serve", help="HTTP frontend over a live simulation")
    _add_cfg_args(serve)
    serve.add_argument("--port", type=int, default=7000)
    serve.add_argument("--tick-hz", type=float, default=10.0)

    run = sub.add_parser("run", help="step N ticks, print summary metrics")
    _add_cfg_args(run)
    run.add_argument("--ticks", type=int, default=500)

    sub.add_parser("bench", help="run the headline benchmark (bench.py)")

    expl = sub.add_parser(
        "explain",
        help="per-event narrative of one group (oracle replay — same seed, "
             "same bits as the kernel)")
    _add_cfg_args(expl)
    expl.add_argument("--group", type=int, default=0)
    expl.add_argument("--ticks", type=str, default="0..100",
                      help="inclusive tick window a..b (replays from 0)")

    args = ap.parse_args(argv)

    if args.command == "explain":
        from raft_kotlin_tpu.api.explain import explain

        lo, _, hi = args.ticks.partition("..")
        lo = int(lo or 0)
        hi = int(hi) if hi else lo
        explain(_cfg_from(args), args.group, lo, hi)
        return 0

    if args.command == "bench":
        # bench.py lives at the repo root, not inside the package — load by path so
        # `python -m raft_kotlin_tpu bench` works from any cwd.
        import importlib.util
        import pathlib

        bench_path = pathlib.Path(__file__).resolve().parent.parent / "bench.py"
        spec = importlib.util.spec_from_file_location("bench", bench_path)
        bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench)
        bench.main()
        return 0

    from raft_kotlin_tpu.api.simulator import Simulator

    if args.command == "serve":
        from raft_kotlin_tpu.api.http_api import RaftHTTPServer

        sim = Simulator(_cfg_from(args), impl=args.impl)
        srv = RaftHTTPServer(sim, port=args.port, tick_hz=args.tick_hz).start()
        print(f"raft_kotlin_tpu serving on http://127.0.0.1:{srv.port} "
              f"({sim.cfg.n_groups} groups x {sim.cfg.n_nodes} nodes, "
              f"tick_hz={args.tick_hz})", file=sys.stderr)
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            srv.stop()
        return 0

    if args.command == "run":
        import numpy as np

        from raft_kotlin_tpu.constants import LEADER
        from raft_kotlin_tpu.models.state import init_state
        from raft_kotlin_tpu.ops.tick import make_run

        import jax

        cfg = _cfg_from(args)
        impl = args.impl
        if impl == "auto":
            from raft_kotlin_tpu.ops.pallas_tick import choose_impl

            impl = choose_impl(cfg)
        st0 = init_state(cfg)
        # Mosaic compiles lazily; run the real scan and fall back to the XLA
        # tick on rejection (bench.measure()'s pattern — no throwaway probe
        # compile, which would double the minutes-long Mosaic startup).
        t0 = time.perf_counter()
        try:
            state, _ = make_run(cfg, args.ticks, trace=False, impl=impl)(st0)
            jax.block_until_ready(state.term)
        except Exception:
            if not (impl == "pallas" and args.impl == "auto"):
                raise
            impl = "xla"
            t0 = time.perf_counter()
            state, _ = make_run(cfg, args.ticks, trace=False, impl="xla")(st0)
            jax.block_until_ready(state.term)
        dt = time.perf_counter() - t0
        roles = np.asarray(state.role)
        print(json.dumps({
            "ticks": args.ticks,
            "groups": cfg.n_groups,
            "elapsed_s": round(dt, 3),
            "group_steps_per_sec": round(cfg.n_groups * args.ticks / dt, 1),
            "impl": impl,
            "groups_with_leader": int(np.sum((roles == LEADER).any(axis=0))),
            "elections_started": int(np.sum(np.asarray(state.rounds))),
            "max_commit": int(np.max(np.asarray(state.commit))),
        }))
        return 0
    return 1


if __name__ == "__main__":
    sys.exit(main())
